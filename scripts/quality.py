"""Model-quality harness: trains the example workflows on real data
through the full loader->workflow->decision->snapshotter graph and
records the reached validation errors in QUALITY.json (committed).

Always runs the offline digits anchor (real handwritten digits bundled
with scikit-learn).  Runs MNIST / CIFAR-10 against the reference's
published quality table (1.48 % / 17.21 %,
/root/reference/docs/source/manualrst_veles_algorithms.rst:31,50) when
their datasets are cached locally or downloadable.

    python scripts/quality.py [--out QUALITY.json] [--backend cpu]
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_example(module_name, backend, snapshot_check=False):
    """Build the example's workflow, attach a snapshotter, run, and
    report {best_error_pct, best_epoch, epochs, seconds}."""
    import importlib

    from veles_tpu.launcher import Launcher
    from veles_tpu.snapshotter import Snapshotter, SnapshotterBase

    module = importlib.import_module(module_name)
    launcher = Launcher()
    workflow = module.build(launcher)

    tmpdir = tempfile.mkdtemp(prefix="quality_snap_")
    snap = Snapshotter(workflow, directory=tmpdir, prefix=module_name,
                       interval=1, time_interval=0, compression="gz")
    snap.link_from(workflow.decision)
    snap.gate_skip = ~workflow.decision.improved

    started = time.time()
    launcher.initialize(device=backend)
    launcher.run()
    elapsed = time.time() - started

    result = {
        "best_error_pct": workflow.decision.best_metric,
        "best_epoch": workflow.decision.best_epoch,
        "epochs": int(workflow.loader.epoch_number),
        "seconds": round(elapsed, 2),
        "backend": backend,
    }
    if snapshot_check:
        # checkpoint/resume proof: the best snapshot reloads and its
        # weights are live (finite) after re-initialize
        restored = SnapshotterBase.import_file(snap.destination)
        relauncher = Launcher()
        restored.workflow = relauncher
        restored.restored_from_snapshot_ = True
        relauncher._workflow = restored
        relauncher.initialize(device=backend)
        import numpy
        restored.forwards[0].weights.map_read()
        assert numpy.isfinite(restored.forwards[0].weights.mem).all()
        result["snapshot_restored"] = True
    return result


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "QUALITY.json"))
    parser.add_argument("--backend", default=os.environ.get(
        "VELES_BACKEND", "cpu"))
    parser.add_argument("--skip-mnist", action="store_true")
    parser.add_argument("--skip-cifar", action="store_true")
    args = parser.parse_args()

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "examples"))

    from veles_tpu.datasets import DatasetNotFound

    report = {"targets": {
        "digits": {"note": "offline anchor, no reference number"},
        "sequence": {"note": "LSTM over digit rows; the reference "
                             "shipped RNN/LSTM untested — no number "
                             "to match, anchor is ours"},
        "conv_autoencoder": {"note": "conv+deconv reconstruction on "
                                     "digits (reference family: conv "
                                     "autoencoders)"},
        "autoencoder": {"reference_rmse": 0.5478,
                        "source": "manualrst_veles_algorithms.rst:69",
                        "note": "reference number is MNIST; offline "
                                "anchor reconstructs 8x8 digits"},
        "mnist": {"reference_error_pct": 1.48,
                  "source": "manualrst_veles_algorithms.rst:31"},
        "cifar10": {"reference_error_pct": 17.21,
                    "source": "manualrst_veles_algorithms.rst:50"},
    }, "results": {}}

    report["results"]["digits"] = run_example(
        "digits", args.backend, snapshot_check=True)
    print("digits: %.2f%% (epoch %d)" % (
        report["results"]["digits"]["best_error_pct"],
        report["results"]["digits"]["best_epoch"]))

    seq = run_example("sequence", args.backend)
    report["results"]["sequence"] = seq
    print("sequence (LSTM): %.2f%% (epoch %d)" % (
        seq["best_error_pct"], seq["best_epoch"]))

    ae = run_example("autoencoder", args.backend)
    ae["best_rmse"] = ae.pop("best_error_pct")
    report["results"]["autoencoder"] = ae
    print("autoencoder: RMSE %.4f (epoch %d)" % (
        ae["best_rmse"], ae["best_epoch"]))

    cae = run_example("conv_autoencoder", args.backend)
    cae["best_rmse"] = cae.pop("best_error_pct")
    report["results"]["conv_autoencoder"] = cae
    print("conv_autoencoder: RMSE %.4f (epoch %d)" % (
        cae["best_rmse"], cae["best_epoch"]))

    for name, skip in (("mnist", args.skip_mnist),
                       ("cifar10", args.skip_cifar)):
        if skip:
            report["results"][name] = {"status": "skipped"}
            continue
        try:
            report["results"][name] = run_example(name, args.backend)
            print("%s: %.2f%%" % (
                name, report["results"][name]["best_error_pct"]))
        except DatasetNotFound as exc:
            report["results"][name] = {"status": "data_unavailable",
                                       "detail": str(exc)}
            print("%s: data unavailable (%s)" % (name, exc))

    with open(args.out, "w") as fout:
        json.dump(report, fout, indent=1, sort_keys=True)
        fout.write("\n")
    print("wrote", args.out)


if __name__ == "__main__":
    main()
