"""Model-quality harness: trains the example workflows on real data
through the full loader->workflow->decision->snapshotter graph and
records the reached validation errors in QUALITY.json (committed).

Always runs the offline anchors (real handwritten digits bundled with
scikit-learn, across MLP/conv/LSTM/autoencoder families).  Runs the
dataset-gated parity anchors — MNIST 1.48 %, CIFAR-10 17.21 %, STL-10
35.10 %, MNIST autoencoder RMSE 0.5478
(/root/reference/docs/source/manualrst_veles_algorithms.rst:31,50,51,69)
— when their datasets are present; ``--skip-datasets`` skips all of
them.

Rows are keyed by backend and path: ``--backend cpu`` writes under
``results`` (the historical CPU key), any other backend under
``results_<backend>`` — all kept in the same file, so a TPU run
records on-chip proof alongside the CPU anchors (round-3 verdict
item 2).  On TPU the DEFAULT path auto-fuses (StandardWorkflow fuses
the train loop into one dispatch per minibatch), so ``results_tpu``
is fused-path evidence; every row carries a ``fused`` flag.
``--fuse`` forces fusing on a backend whose default is per-unit
(rows land under ``results_<backend>_fused``, including cpu);
``--no-fuse`` keeps the per-unit debug path on TPU (rows land under
``results_tpu_unit``).  Anchors no longer in the known set are
dropped from every results_* map on rewrite.  ``--anchors`` selects
a subset (default: all offline anchors + mnist/cifar when data
exists).

    python scripts/quality.py [--out QUALITY.json] [--backend cpu]
                              [--anchors digits,sequence,...]
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_example(module_name, backend, snapshot_check=False,
                fuse=False, no_fuse=False):
    """Build the example's workflow, run it, and report
    {best_error_pct, best_epoch, epochs, seconds}.  With
    ``snapshot_check`` a snapshotter rides the loop (snapshot on every
    improved epoch) and the best snapshot is re-imported afterwards —
    anchors without the flag run snapshot-free, so their ``seconds``
    exclude snapshot overhead."""
    import importlib

    from veles_tpu.launcher import Launcher
    from veles_tpu.snapshotter import Snapshotter, SnapshotterBase

    from veles_tpu.config import root
    if no_fuse:
        root.common.engine.auto_fuse = False
    module = importlib.import_module(module_name)
    launcher = Launcher()
    workflow = module.build(launcher)
    if fuse and getattr(workflow, "fused_trainer", None) is None:
        # force the fused path on a backend whose default is per-unit
        # (on TPU the StandardWorkflow auto-fuses at initialize)
        workflow.fuse()

    # the snapshotter rides the loop only for the anchor that proves
    # restore: each whole-workflow pickle map_reads every param from
    # the device (~1.9 s/snapshot over a tunneled TPU), so attaching
    # it everywhere multiplies on-chip anchor wall time for no
    # additional evidence
    snap = None
    if snapshot_check:
        tmpdir = tempfile.mkdtemp(prefix="quality_snap_")
        snap = Snapshotter(workflow, directory=tmpdir,
                           prefix=module_name, interval=1,
                           time_interval=0, compression="gz")
        snap.link_from(workflow.decision)
        snap.gate_skip = ~workflow.decision.improved

    started = time.time()
    launcher.initialize(device=backend)
    launcher.run()
    elapsed = time.time() - started

    result = {
        "best_error_pct": workflow.decision.best_metric,
        "best_epoch": workflow.decision.best_epoch,
        "epochs": int(workflow.loader.epoch_number),
        "seconds": round(elapsed, 2),
        "backend": backend,
        "fused": getattr(workflow, "fused_trainer", None) is not None,
    }
    if snapshot_check:
        # checkpoint/resume proof: the best snapshot reloads and its
        # weights are live (finite) after re-initialize
        restored = SnapshotterBase.import_file(snap.destination)
        relauncher = Launcher()
        restored.workflow = relauncher
        restored.restored_from_snapshot_ = True
        relauncher._workflow = restored
        relauncher.initialize(device=backend)
        import numpy
        restored.forwards[0].weights.map_read()
        assert numpy.isfinite(restored.forwards[0].weights.mem).all()
        result["snapshot_restored"] = True
    return result


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "QUALITY.json"))
    parser.add_argument("--backend", default=os.environ.get(
        "VELES_BACKEND", "cpu"))
    parser.add_argument("--anchors", default=None,
                        help="comma list; default all")
    parser.add_argument("--fuse", action="store_true",
                        help="force the fused single-dispatch trainer "
                             "on a backend whose default is per-unit "
                             "(rows land under "
                             "results_<backend>_fused, incl. cpu)")
    parser.add_argument("--no-fuse", action="store_true",
                        help="keep the per-unit debug path on TPU "
                             "(rows land under results_tpu_unit)")
    parser.add_argument("--skip-mnist", action="store_true")
    parser.add_argument("--skip-cifar", action="store_true")
    parser.add_argument("--skip-datasets", action="store_true",
                        help="skip every dataset-gated anchor "
                             "(mnist, cifar10, stl10, "
                             "mnist_autoencoder)")
    args = parser.parse_args()

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "examples"))

    from veles_tpu.datasets import DatasetNotFound

    targets = {
        "digits": {"note": "offline anchor, no reference number"},
        "digits_conv": {"note": "conv *classification* through the "
                                "conv/pool stack on digits (reference "
                                "conv numbers are classification, "
                                "manualrst_veles_algorithms.rst:50)"},
        "sequence": {"note": "LSTM over digit rows; the reference "
                             "shipped RNN/LSTM untested — no number "
                             "to match, anchor is ours"},
        "conv_autoencoder": {"note": "conv+deconv reconstruction on "
                                     "digits (reference family: conv "
                                     "autoencoders)"},
        "autoencoder": {"reference_rmse": 0.5478,
                        "source": "manualrst_veles_algorithms.rst:69",
                        "note": "reference number is MNIST; offline "
                                "anchor reconstructs 8x8 digits"},
        "mnist": {"reference_error_pct": 1.48,
                  "source": "manualrst_veles_algorithms.rst:31"},
        "cifar10": {"reference_error_pct": 17.21,
                    "source": "manualrst_veles_algorithms.rst:50"},
        "stl10": {"reference_error_pct": 35.10,
                  "source": "manualrst_veles_algorithms.rst:51"},
        "mnist_autoencoder": {
            "reference_rmse": 0.5478,
            "source": "manualrst_veles_algorithms.rst:69"},
    }

    # merge into the existing record so a TPU pass extends (not
    # clobbers) the committed CPU rows
    report = {"targets": targets, "results": {}}
    if os.path.exists(args.out):
        try:
            with open(args.out) as fin:
                report.update(json.load(fin))
            report["targets"] = targets
        except ValueError:
            pass
    if args.fuse and args.no_fuse:
        parser.error("--fuse and --no-fuse are mutually exclusive")
    base_key = ("results" if args.backend == "cpu"
                else "results_%s" % args.backend)
    if args.fuse:
        # explicit fused suffix always names the backend (cpu included)
        results_key = "results_%s_fused" % args.backend
    elif args.no_fuse and args.backend == "tpu":
        # the TPU default IS fused; the opt-out is the marked path
        results_key = "results_tpu_unit"
    else:
        results_key = base_key
    results = report.setdefault(results_key, {})
    # drop rows for anchors that no longer exist (renamed/removed
    # anchors otherwise live in the record forever)
    for key, rows in list(report.items()):
        if key.startswith("results") and isinstance(rows, dict):
            for stale in set(rows) - set(targets):
                del rows[stale]

    anchors = (args.anchors.split(",") if args.anchors else
               ["digits", "digits_conv", "sequence", "autoencoder",
                "conv_autoencoder", "mnist", "cifar10", "stl10",
                "mnist_autoencoder"])

    rmse_anchors = {"autoencoder", "conv_autoencoder",
                    "mnist_autoencoder"}
    dataset_gated = {"mnist", "cifar10", "stl10", "mnist_autoencoder"}
    for name in anchors:
        if (name == "mnist" and args.skip_mnist
                or name == "cifar10" and args.skip_cifar
                or name in dataset_gated and args.skip_datasets):
            results[name] = {"status": "skipped"}
            continue
        try:
            row = run_example(name, args.backend,
                              snapshot_check=(name == "digits"),
                              fuse=args.fuse, no_fuse=args.no_fuse)
        except DatasetNotFound as exc:
            results[name] = {"status": "data_unavailable",
                             "detail": str(exc)}
            print("%s: data unavailable (%s)" % (name, exc))
            continue
        if name in rmse_anchors:
            row["best_rmse"] = row.pop("best_error_pct")
            print("%s: RMSE %.4f (epoch %d)" % (
                name, row["best_rmse"], row["best_epoch"]))
        else:
            print("%s: %.2f%% (epoch %d)" % (
                name, row["best_error_pct"], row["best_epoch"]))
        results[name] = row

    with open(args.out, "w") as fout:
        json.dump(report, fout, indent=1, sort_keys=True)
        fout.write("\n")
    print("wrote", args.out)


if __name__ == "__main__":
    main()
