"""Preemption soak: SIGKILL real slave processes on a seeded schedule
and prove the elasticity contract with receipts (ELASTIC.json).

The driver trains a small master-slave run where the slave is a REAL
subprocess that preempts itself — the chaos point ``slave.preempt``
(``kill`` = ``os.kill(os.getpid(), SIGKILL)``: no atexit, no goodbye
frame, the closest in-tree stand-in for a preemptible chip being
reclaimed).  Each incarnation's kill point comes from an aK-style
``VELES_CHAOS`` spec derived from one seed; the driver waits out a
seeded ``slave.rejoin_after`` delay and respawns.  Receipts:

- **bit-stable convergence**: the soaked master's final weights are
  bit-identical to a fault-free run of the same seeds (solver state
  ships with every job the same way params do, so momentum layers
  replay bit-faithfully through a respawn too; docs/distributed.md,
  "Exactly-once updates");
- **bounded throughput loss**: soak wall time minus fault-free wall
  time stays under the injected rejoin delays plus a per-preempt
  respawn allowance (subprocess + jax import + workflow build);
- **kill-during-reshard exactly-once**: an in-process run where a
  reshard push severs the conn (``server.reshard=kill``) applies
  exactly as many updates as fault-free, bit-identical weights — no
  update double-applied, none lost.

    python scripts/elastic_soak.py --out ELASTIC.json \
        [--preempts 6] [--max-epochs 10] [--seed 42]

The ``slow``-marked test wrapper (tests/test_elastic.py) runs a
shortened soak through this same driver; the tier-1 smoke variant
lives in-process in that file.
"""

import argparse
import json
import os
import random
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy  # noqa: E402

from veles_tpu import chaos, prng  # noqa: E402
from veles_tpu.chaos import FaultPlan  # noqa: E402
from veles_tpu.loader.fullbatch import FullBatchLoader  # noqa: E402
from veles_tpu.prng import RandomGenerator  # noqa: E402

#: momentum ON: the gd units ship their solver accumulators with every
#: job (nn_units.GradientDescentBase master-slave contract) and the
#: master merges the deltas, so a RESPAWNED slave replays momentum
#: runs bit-equivalently to one that survived — the soak proves it
LAYERS = [
    {"type": "all2all_tanh", "output_sample_shape": 24,
     "learning_rate": 0.05, "gradient_moment": 0.9},
    {"type": "softmax", "output_sample_shape": 4,
     "learning_rate": 0.05, "gradient_moment": 0.9},
]

#: per-preempt respawn allowance for the throughput bound: process
#: spawn + jax import + workflow build + reconnect backoff on CPU CI
RESPAWN_ALLOWANCE_S = 30.0


class SoakLoader(FullBatchLoader):
    """Deterministic 4-class Gaussian blobs (the chaos-suite feed),
    rebuilt identically by every slave incarnation from its seed."""

    def load_data(self):
        self.class_lengths[:] = [0, 64, 256]
        self._calc_class_end_offsets()
        self.create_originals((16,))
        rng = numpy.random.RandomState(99)
        centers = rng.randn(4, 16) * 2.0
        for i in range(self.total_samples):
            label = i % 4
            self.original_data.mem[i] = (
                centers[label] + rng.randn(16) * 0.3)
            self.original_labels[i] = label


def build(mode, seed_key, max_epochs):
    from veles_tpu.backends import Device
    from veles_tpu.dummy import DummyWorkflow
    from veles_tpu.models.nn_workflow import StandardWorkflow
    prng.get().seed(4242)  # identical layer-init streams everywhere
    wf = DummyWorkflow()
    wf.workflow.workflow_mode = mode
    sw = StandardWorkflow(
        wf.workflow, layers=[dict(spec) for spec in LAYERS],
        loader_factory=lambda w: SoakLoader(
            w, minibatch_size=64,
            prng=RandomGenerator(seed_key, seed=7)),
        decision_config=dict(max_epochs=max_epochs),
    )
    sw.initialize(device=Device(backend="cpu"))
    return sw


def master_weights(sw):
    out = []
    for fwd in sw.forwards:
        fwd.weights.map_read()
        out.append(numpy.array(fwd.weights.mem))
    return out


def start_master(max_epochs):
    from veles_tpu.server import Server
    sw = build("master", "soak_m", max_epochs)
    server = Server("127.0.0.1:0", sw)
    sw.workflow.on_workflow_finished = server.on_workflow_finished
    server.start_background()
    assert server.wait_listening(10), server.bind_error
    return sw, server


def spawn_worker(port, max_epochs, chaos_spec):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("VELES_CHAOS", None)
    if chaos_spec:
        env["VELES_CHAOS"] = chaos_spec
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__),
         "--worker", "127.0.0.1:%d" % port,
         "--max-epochs", str(max_epochs)],
        env=env, cwd=REPO,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


def worker_main(address, max_epochs):
    # VELES_CHAOS was parsed at veles_tpu.chaos import: slave.preempt
    # is armed (or not) before the first job ever runs
    from veles_tpu.client import Client
    sw = build("slave", "soak_s", max_epochs)
    Client(address, sw).run()
    return 0


def run_fault_free(max_epochs):
    """The reference leg: same master, ONE clean subprocess slave."""
    sw, server = start_master(max_epochs)
    t0 = time.perf_counter()
    child = spawn_worker(server.port, max_epochs, None)
    done = server._done.wait(1200)
    wall = time.perf_counter() - t0
    try:
        child.wait(30)
    except subprocess.TimeoutExpired:
        child.kill()
        child.wait()
    assert done, "fault-free reference never finished"
    assert bool(sw.decision.complete)
    return {
        "wall_s": round(wall, 2),
        "updates_applied": server.updates_applied,
        "jobs_dispatched": server.jobs_dispatched,
        "weights": master_weights(sw),
        "metrics": [None if m is None else float(m)
                    for m in sw.decision.epoch_metrics],
    }


def run_soak(seed, max_epochs, target_preempts, max_incarnations=60):
    """The soak leg: slaves preempt themselves on the seeded aK
    schedule until ``target_preempts`` SIGKILLs landed, then a clean
    incarnation finishes the run."""
    rng = random.Random(seed)
    # the rejoin cadence is itself a FaultPlan schedule: one nK entry
    # per incarnation, param = seconds to wait before the respawn
    rejoin_plan = FaultPlan(seed=seed)
    for k in range(1, max_incarnations + 1):
        rejoin_plan.add("slave.rejoin_after", "delay", nth=k,
                        param=round(rng.uniform(0.2, 1.0), 3))
    kill_after = [rng.randint(2, 6) for _ in range(max_incarnations)]

    sw, server = start_master(max_epochs)
    events = []
    preempts = rejoins = incarnation = 0
    t0 = time.perf_counter()
    delay_total = 0.0
    child = None
    try:
        while not server._done.is_set():
            assert incarnation < max_incarnations, \
                "soak never converged (%d incarnations)" % incarnation
            if preempts < target_preempts:
                spec = "seed=%d;slave.preempt=kill:a%d:x1" % (
                    seed + incarnation, kill_after[incarnation])
            else:
                spec = None  # clean tail incarnation finishes the run
            child = spawn_worker(server.port, max_epochs, spec)
            incarnation += 1
            while child.poll() is None and \
                    not server._done.wait(0.2):
                pass
            if server._done.is_set():
                break
            rc = child.returncode
            if rc == -signal.SIGKILL:
                preempts += 1
                events.append({"event": "preempt", "incarnation":
                               incarnation, "after_jobs":
                               kill_after[incarnation - 1]})
            else:
                events.append({"event": "exit", "incarnation":
                               incarnation, "rc": rc})
            fault = rejoin_plan.fire("slave.rejoin_after")
            delay = fault.param if fault is not None else 0.5
            delay_total += delay
            time.sleep(delay)
            rejoins += 1
            events.append({"event": "rejoin", "incarnation":
                           incarnation, "delay_s": delay})
    finally:
        if child is not None and child.poll() is None:
            child.terminate()
            try:
                child.wait(15)
            except subprocess.TimeoutExpired:
                child.kill()
                child.wait()
    wall = time.perf_counter() - t0
    assert server._done.wait(60), "soak master never finished"
    assert bool(sw.decision.complete)
    return {
        "wall_s": round(wall, 2),
        "preempts": preempts,
        "rejoins": rejoins,
        "events": events,
        "rejoin_delay_total_s": round(delay_total, 2),
        "updates_applied": server.updates_applied,
        "jobs_dispatched": server.jobs_dispatched,
        "reshards": server.reshards,
        "membership_epoch": server.fleet.membership_epoch,
        "stale_updates": server.stale_updates,
        "duplicates_dropped": server.duplicates_dropped,
        "requeued_minibatches": sw.loader.total_failed,
        "weights": master_weights(sw),
        "metrics": [None if m is None else float(m)
                    for m in sw.decision.epoch_metrics],
    }


def run_kill_during_reshard(max_epochs):
    """In-process exactly-once case: the slave dies mid-run, and the
    reshard push at its REJOIN severs the conn again
    (``server.reshard=kill``).  Same applied-update count and
    bit-identical weights as fault-free = nothing double-applied,
    nothing lost."""
    from veles_tpu.client import Client

    def leg(plan):
        sw_m = build("master", "soak_krr_m", max_epochs)
        sw_s = build("slave", "soak_krr_s", max_epochs)
        from veles_tpu.server import Server
        server = Server("127.0.0.1:0", sw_m)
        sw_m.workflow.on_workflow_finished = server.on_workflow_finished
        server.start_background()
        assert server.wait_listening(10)
        client = Client("127.0.0.1:%d" % server.port, sw_s)
        if plan is not None:
            chaos.install(plan)
        try:
            client.run()
        finally:
            chaos.uninstall()
        assert server._done.wait(60)
        assert bool(sw_m.decision.complete)
        return sw_m, server, client

    ref_sw, ref_server, _ = leg(None)
    plan = (FaultPlan(seed=7)
            .add("client.job", "die", nth=3)
            .add("server.reshard", "kill", nth=2))
    sw, server, client = leg(plan)
    identical = all(
        numpy.array_equal(a, b) for a, b in zip(
            master_weights(ref_sw), master_weights(sw)))
    return {
        "reshard_kills_fired": plan.fired("server.reshard"),
        "sessions": client.sessions_established,
        "updates_applied_fault_free": ref_server.updates_applied,
        "updates_applied": server.updates_applied,
        "double_applies": max(
            0, server.updates_applied - ref_server.updates_applied),
        "stale_updates": server.stale_updates,
        "bit_identical": bool(identical),
    }


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="preemption soak -> ELASTIC.json receipt")
    parser.add_argument("--worker", metavar="HOST:PORT",
                        help="internal: run as a soak slave process")
    parser.add_argument("--out", default=os.path.join(
        REPO, "ELASTIC.json"))
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--preempts", type=int, default=6,
                        help="SIGKILL preemptions before the clean "
                             "tail (events = preempts + rejoins)")
    parser.add_argument("--max-epochs", type=int, default=10)
    args = parser.parse_args(argv)

    if args.worker:
        return worker_main(args.worker, args.max_epochs)

    print("== fault-free reference (one clean subprocess slave) ==")
    ref = run_fault_free(args.max_epochs)
    print("   wall %.1fs, %d updates" % (ref["wall_s"],
                                         ref["updates_applied"]))
    print("== soak: %d seeded SIGKILL preemptions ==" % args.preempts)
    soak = run_soak(args.seed, args.max_epochs, args.preempts)
    print("   wall %.1fs, %d preempts, %d rejoins, %d reshards" % (
        soak["wall_s"], soak["preempts"], soak["rejoins"],
        soak["reshards"]))
    print("== kill-during-reshard exactly-once case ==")
    krr = run_kill_during_reshard(max_epochs=3)

    identical = all(
        numpy.array_equal(a, b)
        for a, b in zip(ref.pop("weights"), soak.pop("weights")))
    overhead = round(soak["wall_s"] - ref["wall_s"], 2)
    bound = round(soak["rejoin_delay_total_s"] +
                  soak["preempts"] * RESPAWN_ALLOWANCE_S, 2)
    receipt = {
        "schema": "elastic-soak-v1",
        "generated_unix": int(time.time()),
        "platform": "cpu (JAX_PLATFORMS=cpu; control-plane receipt — "
                    "the protocol under test is device-agnostic)",
        "seed": args.seed,
        "config": {
            "max_epochs": args.max_epochs,
            "minibatch": 64,
            "train_samples": 256,
            "layers": "all2all_tanh(24)+softmax(4), momentum 0.9 "
                      "(solver accumulators ship with every job; see "
                      "docs/distributed.md, Exactly-once updates)",
        },
        "fault_free": ref,
        "soak": soak,
        "events_total": soak["preempts"] + soak["rejoins"],
        "bit_identical": bool(identical and
                              ref["metrics"] == soak["metrics"]),
        "throughput": {
            "overhead_s": overhead,
            "bound_s": bound,
            "loss_pct": round(100.0 * overhead /
                              max(soak["wall_s"], 1e-9), 1),
            "within_bound": bool(overhead <= bound),
        },
        "kill_during_reshard": krr,
    }
    with open(args.out, "w") as fout:
        json.dump(receipt, fout, indent=1, sort_keys=True)
        fout.write("\n")
    print("wrote %s: %d events, bit_identical=%s, overhead %.1fs "
          "(bound %.1fs), kdr double_applies=%d" % (
              args.out, receipt["events_total"],
              receipt["bit_identical"], overhead, bound,
              krr["double_applies"]))
    ok = (receipt["bit_identical"]
          and receipt["events_total"] >= 10
          and receipt["throughput"]["within_bound"]
          and krr["double_applies"] == 0
          and krr["bit_identical"])
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
