"""Multi-tenant QoS soak -> QOS.json receipt.

The acceptance proof of the QoS layer (docs/serving.md "Multi-tenant
QoS", ISSUE 17), run on the SAME subprocess-host soak machinery as
scripts/fleet_soak.py (this entry is ``fleet_soak.py --tenants`` with
QoS defaults):

- **flood**: a 3x best-effort tenant flood plus seeded per-host
  ``serve.host.stall`` stragglers against steady interactive clients
  through a bounded fleet front — interactive p99 within the SLO
  budget, **0 interactive sheds**, every shed attributed to
  best_effort/batch by the class-ordered eviction contract, every
  interactive answer bit-identical to the sequential reference.
- **canary**: :class:`FleetCanaryController` promotes a good snapshot
  host-by-host and auto-rolls back a class-permuted poison judged on
  real mirrored evidence — **0 failed interactive requests, 0 new
  compiles** either way.

Usage::

    python scripts/qos_soak.py --out QOS.json          # full
    python scripts/qos_soak.py --fast --out /tmp/Q.json  # smoke
    python scripts/qos_soak.py --alerts --out ALERTS.json  # alerting

The fast profile is the slow-marked test in tests/test_qos.py; the
full profile is the committed QOS.json receipt.  ``--alerts`` runs
the burn-rate alerting soak instead (``fleet_soak.run_alert_soak``
-> ALERTS.json): the steady leg must fire zero alerts, the stall-
chaos leg must fire the fleet-scope SLO burn pair with its flight-
recorder + tail-exemplar dump.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

if __name__ == "__main__":
    from scripts import fleet_soak
    argv = list(sys.argv[1:])
    if "--host" not in argv:
        argv.insert(0, "--tenants")
    sys.exit(fleet_soak.main(argv))
