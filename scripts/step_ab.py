"""Whole-step A/B: custom conv VJP vs jax-autodiff conv gradients.

Cross-run throughput on this tunnel swings ~1.4x with congestion, so
the ONLY honest comparison is two programs interleaved in one
process: build the full AlexNet fused train step twice — tracing once
with models.conv.conv2d swapped for the custom-VJP build below and
once with the stock autodiff conv — warm both, then round-robin
dependent-chain slope samples, median per arm.

Usage: python scripts/step_ab.py [--batch 256] [--rounds 4]
                                 [--chain 40] [--model alexnet]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy


def _custom_vjp_conv2d():
    """A conv2d with hand-scheduled gradients (dgrad = lhs-dilated
    conv of the flipped/IO-swapped kernel; wgrad = batch-as-
    contraction conv via ("CHWN", "IHWO", "HWNC") with the forward
    stride as rhs dilation).  Numerically exact vs autodiff; measured
    perf-neutral on the whole step (the receipt models/conv.py's
    docstring cites) — kept here so the A/B stays re-runnable."""
    import functools

    import jax
    from jax import lax

    @functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
    def conv2d(x, w, strides, padding, pet=None):
        return lax.conv_general_dilated(
            x, w, window_strides=strides, padding=padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=pet)

    def fwd(x, w, strides, padding, pet):
        return conv2d(x, w, strides, padding, pet), (x, w)

    def bwd(strides, padding, pet, res, dy):
        x, w = res
        sy, sx = strides
        (pt, _pb), (pl, _pr) = padding
        k_h, k_w = w.shape[0], w.shape[1]
        h, w_sp = x.shape[1], x.shape[2]
        hout, wout = dy.shape[1], dy.shape[2]
        dy = dy.astype(x.dtype)
        dx = lax.conv_general_dilated(
            dy, w[::-1, ::-1].swapaxes(2, 3),
            window_strides=(1, 1),
            padding=((k_h - 1 - pt, h - 1 + pt - (hout - 1) * sy),
                     (k_w - 1 - pl, w_sp - 1 + pl - (wout - 1) * sx)),
            lhs_dilation=(sy, sx),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        dw = lax.conv_general_dilated(
            x, dy, window_strides=(1, 1),
            padding=((pt, (hout - 1) * sy + k_h - h - pt),
                     (pl, (wout - 1) * sx + k_w - w_sp - pl)),
            rhs_dilation=(sy, sx),
            dimension_numbers=("CHWN", "IHWO", "HWNC"))
        return dx, dw.astype(w.dtype)

    conv2d.defvjp(fwd, bwd)
    return conv2d


def build_step(specs, input_shape, batch, dtype_name, classes):
    """One jitted step + chained runner over the real gather path
    (mirrors bench._train_step_images_per_sec)."""
    import jax
    import jax.numpy as jnp

    from bench import _setup_training
    from veles_tpu.compiler import build_train_step
    from veles_tpu.ops.gather import gather_labels, gather_minibatch

    dataset_size = max(1024, batch * 2)
    setup = _setup_training(specs, input_shape, batch, dataset_size,
                            dtype_name, classes)
    plans, state, dataset, labels_all, order, dup, has_dropout = setup
    step = build_train_step(plans, donate=False)
    key = jax.random.PRNGKey(0) if has_dropout else None

    def one(state, dataset, labels_all, order, offset):
        # device buffers ride as ARGUMENTS: a closed-over dataset
        # would inline as a 300+ MB constant and blow the remote
        # compile service's request limit
        idx = jax.lax.dynamic_slice(order, (offset,), (batch,))
        x = gather_minibatch(dataset, idx)
        y = gather_labels(labels_all, idx)
        return step(state, x, y, jnp.float32(batch), key)

    one = jax.jit(one)
    st, m = one(state, dataset, labels_all, order, 0)
    float(m["loss"].astype(jnp.float32))  # warm (fetch, not block)

    def chain(n):
        start = time.perf_counter()
        st = state
        metrics = None
        for i in range(n):
            st, metrics = one(st, dataset, labels_all, order,
                              (i * batch) % (dataset_size - batch))
        float(metrics["loss"].astype(jnp.float32))
        return time.perf_counter() - start

    return chain


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch", type=int, default=256)
    parser.add_argument("--rounds", type=int, default=4)
    parser.add_argument("--chain", type=int, default=40)
    parser.add_argument("--dtype", default="bfloat16")
    parser.add_argument("--model", default="alexnet")
    args = parser.parse_args()

    from veles_tpu.models import conv
    from veles_tpu.models.zoo import alexnet_layers, vgg_layers

    if args.model == "alexnet":
        specs, ishape = alexnet_layers(classes=1000), (227, 227, 3)
    else:
        specs, ishape = (vgg_layers(classes=1000, config="D"),
                         (224, 224, 3))

    stock_conv2d = conv.conv2d
    chains = {}
    for label, conv2d_impl in (("custom_vjp", _custom_vjp_conv2d()),
                               ("autodiff", stock_conv2d)):
        conv.conv2d = conv2d_impl  # trace-time swap
        try:
            chains[label] = build_step(specs, ishape, args.batch,
                                       args.dtype, 1000)
        finally:
            conv.conv2d = stock_conv2d
        print("warmed %s" % label, flush=True)

    samples = {label: [] for label in chains}
    for r in range(args.rounds):
        for label, chain in chains.items():
            t1 = chain(1)
            t2 = chain(args.chain + 1)
            sec = (t2 - t1) / args.chain
            samples[label].append(sec)
            print("round %d %s: %.3f ms/step" % (r, label, sec * 1e3),
                  flush=True)

    out = {}
    for label, vals in samples.items():
        med = float(numpy.median([v for v in vals if v > 0] or vals))
        out[label] = {"ms_per_step": round(med * 1e3, 3),
                      "images_per_sec": round(args.batch / med, 1),
                      "samples_ms": [round(v * 1e3, 3) for v in vals]}
    if out["autodiff"]["ms_per_step"] and \
            out["custom_vjp"]["ms_per_step"]:
        out["speedup"] = round(
            out["autodiff"]["ms_per_step"]
            / out["custom_vjp"]["ms_per_step"], 3)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
