"""Conv-backward scheduling experiments on the real chip.

Round-4 verdict: the AlexNet fused step runs forward at ~71 % MFU but
backward+update at ~36 %; the dominant costs are the dgrad/wgrad of
the 5x5/3x3 conv layers.  This script microbenches each conv layer's
backward under alternative formulations so the winning one can become
a custom_vjp in models/conv.py:

  autodiff   - jax.vjp of the forward conv (what the step uses today)
  explicit   - hand-written dgrad (transposed conv via lhs_dilation) +
               wgrad (batch-as-contraction conv via dimension numbers)
  wgrad_f32  - explicit, with preferred_element_type=f32 on the wgrad
  im2col     - wgrad as conv_general_dilated_patches + one big matmul

Timing: dependent-chain slope (two chain lengths, scalar fetch each)
so tunnel latency cancels — bench.py's methodology.

Usage:  python scripts/bwd_experiments.py [--layers 2,5] [--repeats 20]
"""

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy

# AlexNet conv layer configs at batch 256 (name, in_shape, kernels,
# k, stride, pad)
LAYERS = {
    "0": ((256, 227, 227, 3), 96, 11, 4, 0),
    "2": ((256, 27, 27, 96), 256, 5, 1, 2),
    "4": ((256, 13, 13, 256), 384, 3, 1, 1),
    "5": ((256, 13, 13, 384), 384, 3, 1, 1),
    "6": ((256, 13, 13, 384), 256, 3, 1, 1),
}


def conv_fwd(x, w, stride, pad):
    from jax import lax
    return lax.conv_general_dilated(
        x, w, window_strides=(stride, stride),
        padding=((pad, pad), (pad, pad)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def explicit_dgrad(dy, w, x_shape, stride, pad):
    """dX via transposed conv: dilate dy by the stride, convolve with
    the spatially-flipped kernel, I/O swapped."""
    from jax import lax
    k = w.shape[0]
    h = x_shape[1]
    hout = dy.shape[1]
    lo = k - 1 - pad
    hi = h - (hout - 1) * stride - 1 + pad
    w_t = w[::-1, ::-1].swapaxes(2, 3)  # flip spatial, swap I/O
    return lax.conv_general_dilated(
        dy, w_t, window_strides=(1, 1),
        padding=((lo, hi), (lo, hi)),
        lhs_dilation=(stride, stride),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def explicit_wgrad(x, dy, k, stride, pad, pet=None):
    """dW via batch-as-contraction conv: lhs batch <- channels,
    contraction <- batch, rhs dilation <- forward stride."""
    from jax import lax
    h = x.shape[1]
    hout = dy.shape[1]
    hi = (hout - 1) * stride + k - h - pad
    return lax.conv_general_dilated(
        x, dy, window_strides=(1, 1),
        padding=((pad, hi), (pad, hi)),
        rhs_dilation=(stride, stride),
        dimension_numbers=("CHWN", "IHWO", "HWNC"),
        preferred_element_type=pet)


def im2col_wgrad(x, dy, k, stride, pad):
    """dW as patch extraction + one matmul on the MXU."""
    import jax.numpy as jnp
    from jax import lax
    n, h, w_sp, c = x.shape
    patches = lax.conv_general_dilated_patches(
        x, (k, k), (stride, stride), ((pad, pad), (pad, pad)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    # patches: (N, Hout, Wout, C*k*k) with feature order C-major
    hout, wout = patches.shape[1], patches.shape[2]
    pm = patches.reshape(n * hout * wout, -1)
    dm = dy.reshape(n * hout * wout, -1)
    dw = jnp.dot(pm.T, dm, preferred_element_type=jnp.float32)
    # feature order of patches is (C, kh, kw) -> reshape + transpose
    dw = dw.reshape(c, k, k, dy.shape[3]).transpose(1, 2, 0, 3)
    return dw.astype(x.dtype)


def make_chained(core, x0):
    """Wrap ``core(x) -> pytree`` as jitted ``x -> x`` whose output
    carries a data dependency on EVERY output leaf.

    Two lazy-tunnel gotchas this defends against (both produced
    fictitious sub-roofline timings in the first run of this script):
    ``block_until_ready`` does not force execution — only a value
    fetch does; and INDEPENDENT repeated calls are not all forced by
    fetching the last one — the chain must be dependent.  The
    summed-leaves perturbation (scaled to underflow) creates the
    dependency without changing x."""
    import jax
    import jax.numpy as jnp

    def step(x):
        outs = core(x)
        s = sum(jnp.sum(leaf.astype(jnp.float32))
                for leaf in jax.tree.leaves(outs))
        return x + (s * 1e-30).astype(x.dtype)

    return jax.jit(step)


def slope_sample(fn, x0, n2):
    """One dependent-chain slope sample, ended by a scalar fetch
    (bench.py's methodology).  Caller must have warmed fn."""
    import jax.numpy as jnp

    def chain(m):
        start = time.perf_counter()
        x = x0
        for _ in range(m):
            x = fn(x)
        float(x.ravel()[0].astype(jnp.float32))
        return time.perf_counter() - start

    t1 = chain(1)
    t2 = chain(n2 + 1)
    return (t2 - t1) / n2


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--layers", default="2,5")
    parser.add_argument("--repeats", type=int, default=100,
                        help="chain length per slope sample (>=100: "
                             "short chains invert rankings on this "
                             "tunnel)")
    parser.add_argument("--rounds", type=int, default=3,
                        help="round-robin sampling rounds")
    parser.add_argument(
        "--variants",
        default="fwd,autodiff_bwd,explicit_bwd",
        help="comma list from fwd,autodiff_bwd,explicit_bwd,"
             "explicit_bwd_f32wg,im2col_bwd")
    parser.add_argument("--dtype", default="bfloat16")
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp

    dtype = getattr(jnp, args.dtype)
    rng = numpy.random.RandomState(0)
    report = {}
    for name in args.layers.split(","):
        in_shape, kernels, k, stride, pad = LAYERS[name.strip()]
        c_in = in_shape[3]
        x = jax.device_put(
            (rng.rand(*in_shape) - 0.5).astype(numpy.float32) * 0.1
        ).astype(dtype)
        w = jax.device_put(
            (rng.rand(k, k, c_in, kernels) - 0.5).astype(
                numpy.float32) * 0.05).astype(dtype)
        y = conv_fwd(x, w, stride, pad)
        dy = (y * 0 + jnp.asarray(
            rng.rand(*y.shape).astype(numpy.float32) * 0.01,
            dtype)).astype(dtype)
        dy = jax.block_until_ready(dy)
        flops_fwd = 2.0 * numpy.prod(y.shape) * k * k * c_in
        row = {"in": list(in_shape), "kernels": kernels, "k": k,
               "stride": stride,
               "fwd_gflops": round(flops_fwd / 1e9, 1)}

        fwd = jax.jit(functools.partial(conv_fwd, stride=stride,
                                        pad=pad))

        def autodiff_bwd(x, w, dy):
            _, vjp = jax.vjp(lambda xx, ww: fwd(xx, ww), x, w)
            return vjp(dy)

        auto = jax.jit(autodiff_bwd)

        expl = jax.jit(lambda x, w, dy: (
            explicit_dgrad(dy, w, x.shape, stride, pad),
            explicit_wgrad(x, dy, k, stride, pad)))
        im2 = jax.jit(lambda x, w, dy: (
            explicit_dgrad(dy, w, x.shape, stride, pad),
            im2col_wgrad(x, dy, k, stride, pad)))

        # numeric parity before timing anything
        a_dx, a_dw = auto(x, w, dy)
        for label, fn in (("explicit", expl), ("im2col", im2)):
            e_dx, e_dw = fn(x, w, dy)
            err_dx = float(jnp.max(jnp.abs(
                a_dx.astype(jnp.float32) - e_dx.astype(jnp.float32))))
            err_dw = float(jnp.max(jnp.abs(
                a_dw.astype(jnp.float32) - e_dw.astype(jnp.float32))))
            scale = float(jnp.max(jnp.abs(
                a_dw.astype(jnp.float32)))) or 1.0
            row["%s_max_rel_err_dw" % label] = round(err_dw / scale, 5)
            row.setdefault("parity", {})[label] = {
                "dx": round(err_dx, 5), "dw": round(err_dw, 5)}

        all_variants = {
            "fwd": lambda xx: fwd(xx, w),
            "autodiff_bwd": lambda xx: autodiff_bwd(xx, w, dy),
            "explicit_bwd": lambda xx: (
                explicit_dgrad(dy, w, xx.shape, stride, pad),
                explicit_wgrad(xx, dy, k, stride, pad)),
            "explicit_bwd_f32wg": lambda xx: (
                explicit_dgrad(dy, w, xx.shape, stride, pad),
                explicit_wgrad(xx, dy, k, stride, pad,
                               pet=jnp.float32)),
            "im2col_bwd": lambda xx: (
                explicit_dgrad(dy, w, xx.shape, stride, pad),
                im2col_wgrad(xx, dy, k, stride, pad)),
        }
        wanted = [v.strip() for v in args.variants.split(",")
                  if v.strip()]
        unknown = [v for v in wanted if v not in all_variants]
        if unknown:
            raise SystemExit(
                "unknown variants %s (choose from %s)" % (
                    unknown, ", ".join(all_variants)))
        chosen = {lbl: make_chained(core, x)
                  for lbl, core in all_variants.items()
                  if lbl in wanted}
        # sequential warmup (concurrent first-execs serialize anyway),
        # then ROUND-ROBIN interleaved sampling: congestion drifts
        # minute to minute, so per-variant sequential sampling is not
        # comparable — one slope sample of every variant per round,
        # median over all rounds
        import jax.numpy as _jnp
        for lbl, fn in chosen.items():
            float(fn(x).ravel()[0].astype(_jnp.float32))
        samples = {lbl: [] for lbl in chosen}
        for _ in range(args.rounds):
            for lbl, fn in chosen.items():
                try:
                    samples[lbl].append(
                        slope_sample(fn, x, args.repeats))
                except Exception as exc:
                    row[lbl + "_error"] = repr(exc)
        for lbl, vals in samples.items():
            positive = [v for v in vals if v > 0]
            if not positive or len(positive) < len(vals) // 2 + 1:
                row[lbl + "_ms"] = None
                row[lbl + "_samples_ms"] = [round(v * 1e3, 3)
                                            for v in vals]
                continue
            med = float(numpy.median(vals))
            row[lbl + "_ms"] = round(med * 1e3, 3)
            row[lbl + "_samples_ms"] = [round(v * 1e3, 3)
                                        for v in vals]
            flops = flops_fwd if lbl == "fwd" else 2.0 * flops_fwd
            row[lbl + "_tflops"] = round(flops / med / 1e12, 1)
        report["layer_%s" % name] = row
        print(json.dumps({("layer_%s" % name): row}), flush=True)

    print(json.dumps(report, indent=1))


if __name__ == "__main__":
    main()
