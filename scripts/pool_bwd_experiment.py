"""Max-pool backward scheduling experiment (chip).

XLA:TPU lowers the autodiff max-pool gradient to select-and-scatter,
a historically slow op.  Candidate: pool via dilated patches + argmax
one-hot, whose backward is a conv-style gather.  Interleaved
round-robin dependent chains (see bwd_experiments.py for the
methodology rules).

Usage: python scripts/pool_bwd_experiment.py
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy

from bwd_experiments import make_chained, slope_sample  # noqa: E402

# AlexNet pools at batch 256: (in_shape, k, stride); all exact-fit
POOLS = {
    "1": ((256, 55, 55, 96), 3, 2),
    "3": ((256, 27, 27, 256), 3, 2),
    "7": ((256, 13, 13, 256), 3, 2),
}


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    rng = numpy.random.RandomState(0)
    report = {}
    for name, (in_shape, k, s) in POOLS.items():
        in_shape = tuple(in_shape)
        x = jax.device_put(
            rng.rand(*in_shape).astype(numpy.float32)).astype(
                jnp.bfloat16)

        def pool_rw(xx):
            return lax.reduce_window(
                xx, -numpy.inf, lax.max,
                window_dimensions=(1, k, k, 1),
                window_strides=(1, s, s, 1),
                padding=((0, 0), (0, 0), (0, 0), (0, 0)))

        def pool_patches(xx):
            n, h, w, c = xx.shape
            p = lax.conv_general_dilated_patches(
                xx, (k, k), (s, s), ((0, 0), (0, 0)),
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            oh, ow = p.shape[1], p.shape[2]
            p = p.reshape(n, oh, ow, c, k * k)
            return p.max(axis=-1)

        y = pool_rw(x)
        dy = jax.device_put(
            rng.rand(*y.shape).astype(numpy.float32)).astype(
                jnp.bfloat16)

        def bwd(pool):
            def f(xx):
                _, vjp = jax.vjp(pool, xx)
                return vjp(dy)
            return f

        # Fidelity check on a TIE-FREE f32 input (shuffled arange/n at
        # batch 8: every value distinct and distinctly
        # f32-representable), comparing elementwise in f32 so
        # differing tie-breaks can't masquerade as routing errors.
        # Measured finding: the patches path is NOT value-exact — the
        # extraction conv (and its transpose in the backward) runs
        # through bf16-class precision, quantizing forward values
        # (err ~2e-3 where it bites, e.g. 0.904321 -> 0.90625) and
        # perturbing the routed gradient values.  That makes
        # select-and-scatter the winner on BOTH axes: ~6x faster AND
        # exact; the rows below record both deltas
        # (bwd_value_delta_fraction counts elements whose gradient
        # differs by >1e-7 — quantization of routed values and/or
        # mis-routed windows).
        pshape = (8,) + in_shape[1:]
        n_el = int(numpy.prod(pshape))
        xf = jnp.asarray(
            (rng.permutation(n_el).astype(numpy.float32) / n_el)
            .reshape(pshape))
        yf_rw = pool_rw(xf)
        yf_p = pool_patches(xf)
        row = {"in": list(in_shape), "k": k, "stride": s,
               "patches_fwd_quantization_err": round(float(
                   jnp.max(jnp.abs(yf_rw - yf_p))), 6)}
        dyf = jnp.asarray(rng.rand(
            *yf_rw.shape).astype(numpy.float32))
        ga = jax.jit(lambda xx: jax.vjp(pool_rw, xx)[1](dyf)[0])(xf)
        gp = jax.jit(lambda xx: jax.vjp(
            pool_patches, xx)[1](dyf)[0])(xf)
        mismatch = float(jnp.mean(
            (jnp.abs(ga - gp) > 1e-7).astype(jnp.float32)))
        row["bwd_value_delta_fraction"] = round(mismatch, 6)

        variants = {
            "fwd_rw": pool_rw,
            "fwd_patches": pool_patches,
            "bwd_selectscatter": bwd(pool_rw),
            "bwd_patches": bwd(pool_patches),
        }
        chained = {lbl: make_chained(fn, x)
                   for lbl, fn in variants.items()}
        for lbl, fn in chained.items():
            float(fn(x).ravel()[0].astype(jnp.float32))  # warm
        samples = {lbl: [] for lbl in chained}
        for _ in range(4):
            for lbl, fn in chained.items():
                samples[lbl].append(slope_sample(fn, x, 100))
        for lbl, vals in samples.items():
            # positive MAJORITY gate (bwd_experiments rule): a noise-
            # dominated sample set must report None, not a median of
            # negatives
            positive = [v for v in vals if v > 0]
            ok = len(positive) >= len(vals) // 2 + 1
            med = float(numpy.median(vals)) if ok else None
            row[lbl + "_ms"] = (round(med * 1e3, 3)
                                if med and med > 0 else None)
            row[lbl + "_samples_ms"] = [round(v * 1e3, 3)
                                        for v in vals]
        report["pool_%s" % name] = row
        print(json.dumps({("pool_%s" % name): row}), flush=True)
    print(json.dumps(report, indent=1))


if __name__ == "__main__":
    main()
