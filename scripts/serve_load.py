"""Closed-loop load generator for the serving path -> BENCH_serve.json.

The TPU in-datacenter paper's framing: inference is LATENCY-bound —
the application sets a response-time budget and the interesting number
is how much throughput the server sustains before the tail percentiles
blow through it, not the unconstrained mean throughput.  So this
driver runs a *closed loop*: ``C`` clients each keep exactly one
request in flight (send, wait, repeat), and the sweep raises ``C``
until added concurrency stops buying throughput — the knee of the
latency-throughput curve.  Every row carries p50/p95/p99 request
latency; the headline is the knee row and the batched-vs-sequential
throughput delta there.

Two sweeps over a random-parameter MNIST-sized MLP (784-256-10 —
serving performance does not depend on the weight values):

- the HEADLINE sweep drives the continuous batcher in-process (the
  real serving queue, staging, SLO watch and dispatch, minus the
  Python HTTP stack): on a CPU host the tornado+json transport costs
  ~7 ms/request and would bury the millisecond-scale batching effect
  the sweep exists to measure (measured: in-process knee ~3.7k rps vs
  ~150 rps through local HTTP — the transport, not the engine, is the
  HTTP ceiling);
- an HTTP sweep over the full service front is recorded alongside as
  the transport characterization (``http_rows``).  ``--url`` points it
  at an externally started ``python -m veles_tpu.serve`` instead.

    python scripts/serve_load.py              # full sweep -> BENCH_serve.json
    python scripts/serve_load.py --quick      # CI-sized sweep
"""

import argparse
import http.client
import json
import os
import sys
import threading
import time
import urllib.parse

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy  # noqa: E402


def _build_service(ladder, max_delay_ms, slo_p50_ms, slo_p99_ms):
    from veles_tpu.backends import Device
    from veles_tpu.compiler import LayerPlan
    from veles_tpu.models.all2all import All2AllSoftmax, All2AllTanh
    from veles_tpu.serve import AOTEngine, ServeService

    rng = numpy.random.RandomState(0)
    fan_in, hidden, classes = 784, 256, 10
    plans = [LayerPlan(All2AllTanh), LayerPlan(All2AllSoftmax)]
    params = [
        {"weights": rng.rand(fan_in, hidden).astype(numpy.float32),
         "bias": numpy.zeros(hidden, numpy.float32)},
        {"weights": rng.rand(hidden, classes).astype(numpy.float32),
         "bias": numpy.zeros(classes, numpy.float32)},
    ]
    engine = AOTEngine(plans, params, (fan_in,), ladder=ladder,
                       device=Device())
    receipt = engine.compile()
    service = ServeService(
        engine, max_delay_s=max_delay_ms / 1e3, max_queue=1024,
        executor_workers=128, slo_p50_ms=slo_p50_ms,
        slo_p99_ms=slo_p99_ms)
    service.start_background()
    return service, engine, receipt, (fan_in,)


def _closed_loop(url, payloads, clients, duration):
    """``clients`` closed-loop workers against ``url`` for ``duration``
    seconds; returns (latencies_s, errors, elapsed_s).  Each worker
    keeps ONE persistent connection (a closed-loop client models a
    service caller, and per-request TCP setup would swamp the
    millisecond-scale latencies being measured)."""
    parsed = urllib.parse.urlsplit(url)
    latencies, errors, lock = [], [0], threading.Lock()
    stop_at = time.perf_counter() + duration

    def worker(k):
        conn = http.client.HTTPConnection(
            parsed.hostname, parsed.port, timeout=30)
        mine = []
        n = 0
        while time.perf_counter() < stop_at:
            body = payloads[(k * 131 + n) % len(payloads)]
            n += 1
            t0 = time.perf_counter()
            try:
                conn.request("POST", parsed.path, body=body,
                             headers={"Content-Type":
                                      "application/json"})
                resp = conn.getresponse()
                resp.read()
                if resp.status != 200:
                    raise RuntimeError("HTTP %d" % resp.status)
            except Exception:
                with lock:
                    errors[0] += 1
                conn.close()  # reconnect on the next iteration
                continue
            mine.append(time.perf_counter() - t0)
        conn.close()
        with lock:
            latencies.extend(mine)

    threads = [threading.Thread(target=worker, args=(k,))
               for k in range(clients)]
    start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return latencies, errors[0], time.perf_counter() - start


def _closed_loop_inprocess(batcher, samples, clients, duration):
    """In-process closed loop: ``clients`` workers each keep one
    request in flight through the continuous batcher."""
    latencies, errors, lock = [], [0], threading.Lock()
    stop_at = time.perf_counter() + duration

    def worker(k):
        mine = []
        n = 0
        while time.perf_counter() < stop_at:
            x = samples[(k * 131 + n) % len(samples)]
            n += 1
            t0 = time.perf_counter()
            try:
                batcher.infer(x, timeout=30.0)
            except Exception:
                with lock:
                    errors[0] += 1
                continue
            mine.append(time.perf_counter() - t0)
        with lock:
            latencies.extend(mine)

    threads = [threading.Thread(target=worker, args=(k,))
               for k in range(clients)]
    start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return latencies, errors[0], time.perf_counter() - start


def _row(clients, lat, errors, elapsed):
    from veles_tpu.observe.metrics import percentiles
    return {
        "offered_concurrency": clients,
        "completed": len(lat),
        "errors": errors,
        "throughput_rps": round(len(lat) / elapsed, 1),
        **{p: round(v * 1e3, 3)
           for p, v in percentiles(lat).items()},
    }


def run_sweep_inprocess(batcher, sample_shape, levels, duration):
    rng = numpy.random.RandomState(7)
    samples = [rng.rand(*sample_shape).astype(numpy.float32)
               for _ in range(64)]
    _closed_loop_inprocess(batcher, samples, 2, 0.3)  # warm-up
    rows = []
    for clients in levels:
        row = _row(clients, *_closed_loop_inprocess(
            batcher, samples, clients, duration))
        rows.append(row)
        print(json.dumps(row))
    return rows


def run_sweep_http(url, sample_shape, levels, duration):
    rng = numpy.random.RandomState(7)
    payloads = [json.dumps(
        {"input": rng.rand(*sample_shape).round(6).tolist()}).encode()
        for _ in range(32)]
    # warm the HTTP path (connection setup, first dispatch) off the record
    _closed_loop(url, payloads, clients=2, duration=0.3)
    rows = []
    for clients in levels:
        row = _row(clients, *_closed_loop(
            url, payloads, clients, duration))
        rows.append(row)
        print(json.dumps({"http": row}))
    return rows


def find_knee(rows, gain_floor=1.10):
    """The knee row: the last sweep level whose throughput still beat
    the previous level by >= ``gain_floor`` — past it, extra offered
    load only buys queueing latency."""
    knee = rows[0]
    for prev, row in zip(rows, rows[1:]):
        if row["throughput_rps"] >= prev["throughput_rps"] * gain_floor:
            knee = row
        else:
            break
    return knee


def sequential_baseline(engine, sample_shape, duration):
    """In-process single-sample loop through the same AOT engine: the
    no-batching reference the knee-throughput delta is quoted against."""
    from veles_tpu.observe.metrics import percentiles
    rng = numpy.random.RandomState(9)
    xs = rng.rand(64, *sample_shape).astype(numpy.float32)
    lat = []
    stop_at = time.perf_counter() + duration
    n = 0
    while time.perf_counter() < stop_at:
        t0 = time.perf_counter()
        engine.infer(xs[n % len(xs)])
        lat.append(time.perf_counter() - t0)
        n += 1
    ps = percentiles(lat)
    return {"requests_per_sec": round(len(lat) / duration, 1),
            **{p: round(v * 1e3, 3) for p, v in ps.items()}}


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--url", default=None,
                        help="existing /infer endpoint (default: "
                        "start an in-process demo service)")
    parser.add_argument("--quick", action="store_true",
                        help="CI-sized sweep (shorter levels)")
    parser.add_argument("--out", default="BENCH_serve.json")
    parser.add_argument("--duration", type=float, default=None,
                        help="seconds per sweep level")
    parser.add_argument("--max-delay-ms", type=float, default=2.0)
    parser.add_argument("--slo-p50-ms", type=float, default=50.0)
    parser.add_argument("--slo-p99-ms", type=float, default=200.0)
    args = parser.parse_args(argv)

    levels = [1, 2, 4, 8, 16, 32] if args.quick else \
        [1, 2, 4, 8, 16, 32, 64]
    http_levels = levels[:4] if args.quick else levels[:5]
    duration = args.duration or (1.0 if args.quick else 3.0)
    ladder = (1, 8, 32, 128)

    service, engine, receipt, sample_shape = _build_service(
        ladder, args.max_delay_ms, args.slo_p50_ms, args.slo_p99_ms)
    url = args.url or "http://127.0.0.1:%d/infer" % service.port
    try:
        # headline: the batcher under in-process closed-loop load
        rows = run_sweep_inprocess(service.batcher, sample_shape,
                                   levels, duration)
        knee = find_knee(rows)
        sequential = sequential_baseline(engine, sample_shape, duration)
        # transport characterization: the same service over HTTP
        http_rows = run_sweep_http(url, sample_shape, http_levels,
                                   duration)
        from veles_tpu.serve import serve_snapshot
        record = {
            "kind": "serve_bench",
            "schema": 1,
            "framing": "closed-loop latency-bound sweep; percentiles "
                       "are the headline (TPU in-datacenter paper), "
                       "throughput is reported AT the latency knee",
            "model": "mlp_784_256_10_random_params",
            "ladder": list(ladder),
            "max_delay_ms": args.max_delay_ms,
            "duration_per_level_s": duration,
            "rows": rows,
            "knee": knee,
            "sequential_single_sample": sequential,
            "batched_vs_sequential_x": round(
                knee["throughput_rps"]
                / sequential["requests_per_sec"], 2),
            "http_rows": http_rows,
            "http_note": "per-request localhost HTTP costs ~7 ms of "
                         "tornado+json+GIL on this host; the HTTP "
                         "rows characterize that transport, the "
                         "in-process rows the serving engine",
            "compile_receipt": receipt,
            "serve_health_at_end": serve_snapshot() or None,
        }
        with open(args.out, "w") as fout:
            json.dump(record, fout, indent=1)
        print("knee: %s" % json.dumps(knee))
        print("sequential: %s  batched-vs-sequential at knee: %.2fx"
              % (json.dumps(sequential),
                 record["batched_vs_sequential_x"]))
        print("wrote %s" % args.out)
    finally:
        service.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
