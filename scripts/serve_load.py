"""Closed-loop load generator for the serving path -> BENCH_serve.json.

The TPU in-datacenter paper's framing: inference is LATENCY-bound —
the application sets a response-time budget and the interesting number
is how much throughput the server sustains before the tail percentiles
blow through it, not the unconstrained mean throughput.  So this
driver runs a *closed loop*: ``C`` clients each keep exactly one
request in flight (send, wait, repeat), and the sweep raises ``C``
until added concurrency stops buying throughput — the knee of the
latency-throughput curve.  Every row carries p50/p95/p99 request
latency; the headline is the knee row and the transport/replica deltas
there.

Four sweeps over a random-parameter MNIST-sized MLP (784-256-10 —
serving performance does not depend on the weight values):

- the ENGINE sweep drives the continuous batcher in-process (the real
  serving queue, staging, SLO watch and dispatch, minus any wire);
- the JSON sweep goes through the tornado front — the transport whose
  ~7 ms/request of base-10 text encode/decode capped the PR 7 record;
- the BINARY sweep goes through the frame transport
  (serve/transport.py): same service, same batcher, raw tensor bytes
  + same-host shm payload bypass — the json-vs-binary rows ARE the
  transport receipt;
- the FLEET sweep measures multi-replica routing at a fixed
  latency-optimal dispatch rung.  **CPU-harness honesty**: this
  container cannot co-run N real compute streams (measured: two
  engines dispatching concurrently on the 2-core host peak at ~1.3x
  one engine — XLA:CPU's shared thread pool IS the chip), so the
  fleet sweep emulates per-chip dispatch latency: every dispatch
  still runs the REAL engine (bit-identity asserted separately with
  no emulation) and then pads to ``--emulate-device-ms`` of device
  time, exactly the regime of one engine per real accelerator.  The
  raw concurrent-compute ceiling is recorded next to the result; the
  real-chip receipt stays a ROADMAP item, like every other TPU
  number in this repo.

    python scripts/serve_load.py              # full sweep -> BENCH_serve.json
    python scripts/serve_load.py --quick      # CI-sized sweep
"""

import argparse
import http.client
import json
import os
import sys
import threading
import time
import urllib.parse

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy  # noqa: E402


def _ensure_virtual_devices(count):
    """The replica fleet needs N visible devices; on a CPU host that
    means the XLA host-platform override, which must land before jax
    initializes (this script imports veles_tpu lazily for exactly this
    reason)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=%d"
            % count).strip()


def _model_spec():
    from veles_tpu.compiler import LayerPlan
    from veles_tpu.models.all2all import All2AllSoftmax, All2AllTanh

    rng = numpy.random.RandomState(0)
    fan_in, hidden, classes = 784, 256, 10
    plans = [LayerPlan(All2AllTanh), LayerPlan(All2AllSoftmax)]
    params = [
        {"weights": rng.rand(fan_in, hidden).astype(numpy.float32),
         "bias": numpy.zeros(hidden, numpy.float32)},
        {"weights": rng.rand(hidden, classes).astype(numpy.float32),
         "bias": numpy.zeros(classes, numpy.float32)},
    ]
    return plans, params, (fan_in,)


def _build_service(ladder, max_delay_ms, slo_p50_ms, slo_p99_ms):
    from veles_tpu.serve import ReplicaPool, ServeService

    plans, params, sample_shape = _model_spec()
    pool = ReplicaPool(
        plans, params, sample_shape, replicas=1, ladder=ladder,
        max_delay_s=max_delay_ms / 1e3, max_queue=4096,
        slo_p50_ms=slo_p50_ms, slo_p99_ms=slo_p99_ms)
    receipt = pool.compile()
    service = ServeService(pool, executor_workers=128,
                           transport_port=0)
    service.start_background()
    return service, pool, receipt, sample_shape


def _run_clients(worker, clients):
    threads = [threading.Thread(target=worker, args=(k,))
               for k in range(clients)]
    start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return time.perf_counter() - start


def _closed_loop(url, payloads, clients, duration):
    """``clients`` closed-loop workers against ``url`` for ``duration``
    seconds; returns (latencies_s, errors, elapsed_s).  Each worker
    keeps ONE persistent connection (a closed-loop client models a
    service caller, and per-request TCP setup would swamp the
    millisecond-scale latencies being measured)."""
    parsed = urllib.parse.urlsplit(url)
    latencies, errors, lock = [], [0], threading.Lock()
    stop_at = time.perf_counter() + duration

    def worker(k):
        conn = http.client.HTTPConnection(
            parsed.hostname, parsed.port, timeout=30)
        mine = []
        n = 0
        while time.perf_counter() < stop_at:
            body = payloads[(k * 131 + n) % len(payloads)]
            n += 1
            t0 = time.perf_counter()
            try:
                conn.request("POST", parsed.path, body=body,
                             headers={"Content-Type":
                                      "application/json"})
                resp = conn.getresponse()
                resp.read()
                if resp.status != 200:
                    raise RuntimeError("HTTP %d" % resp.status)
            except Exception:
                with lock:
                    errors[0] += 1
                conn.close()  # reconnect on the next iteration
                continue
            mine.append(time.perf_counter() - t0)
        conn.close()
        with lock:
            latencies.extend(mine)

    elapsed = _run_clients(worker, clients)
    return latencies, errors[0], elapsed


def _closed_loop_binary(port, samples, clients, duration, secret=None):
    """Closed loop over the binary frame transport: one persistent
    connection (and, same-host, one shm channel pair) per worker."""
    from veles_tpu.serve import BinaryTransportClient
    latencies, errors, lock = [], [0], threading.Lock()
    shm_used = [False]
    stop_at = time.perf_counter() + duration

    def worker(k):
        cli = BinaryTransportClient(port=port, secret=secret)
        if cli.shm_active:
            shm_used[0] = True
        mine = []
        n = 0
        while time.perf_counter() < stop_at:
            x = samples[(k * 131 + n) % len(samples)]
            n += 1
            t0 = time.perf_counter()
            try:
                cli.infer(x)
            except Exception:
                with lock:
                    errors[0] += 1
                continue
            mine.append(time.perf_counter() - t0)
        cli.close()
        with lock:
            latencies.extend(mine)

    elapsed = _run_clients(worker, clients)
    return latencies, errors[0], elapsed, shm_used[0]


def _closed_loop_inprocess(batcher, samples, clients, duration):
    """In-process closed loop: ``clients`` workers each keep one
    request in flight through the continuous batcher (or pool)."""
    latencies, errors, lock = [], [0], threading.Lock()
    stop_at = time.perf_counter() + duration

    def worker(k):
        mine = []
        n = 0
        while time.perf_counter() < stop_at:
            x = samples[(k * 131 + n) % len(samples)]
            n += 1
            t0 = time.perf_counter()
            try:
                batcher.infer(x, timeout=30.0)
            except Exception:
                with lock:
                    errors[0] += 1
                continue
            mine.append(time.perf_counter() - t0)
        with lock:
            latencies.extend(mine)

    elapsed = _run_clients(worker, clients)
    return latencies, errors[0], elapsed


def _row(clients, lat, errors, elapsed):
    from veles_tpu.observe.metrics import percentiles
    return {
        "offered_concurrency": clients,
        "completed": len(lat),
        "errors": errors,
        "throughput_rps": round(len(lat) / elapsed, 1),
        **{p: round(v * 1e3, 3)
           for p, v in percentiles(lat).items()},
    }


def _samples(sample_shape, n=64):
    rng = numpy.random.RandomState(7)
    return [rng.rand(*sample_shape).astype(numpy.float32)
            for _ in range(n)]


def run_sweep_inprocess(batcher, sample_shape, levels, duration):
    samples = _samples(sample_shape)
    _closed_loop_inprocess(batcher, samples, 2, 0.3)  # warm-up
    rows = []
    for clients in levels:
        row = _row(clients, *_closed_loop_inprocess(
            batcher, samples, clients, duration))
        rows.append(row)
        print(json.dumps(row))
    return rows


def run_sweep_http(url, sample_shape, levels, duration):
    rng = numpy.random.RandomState(7)
    payloads = [json.dumps(
        {"input": rng.rand(*sample_shape).round(6).tolist()}).encode()
        for _ in range(32)]
    # warm the HTTP path (connection setup, first dispatch) off the record
    _closed_loop(url, payloads, clients=2, duration=0.3)
    rows = []
    for clients in levels:
        row = _row(clients, *_closed_loop(
            url, payloads, clients, duration))
        rows.append(row)
        print(json.dumps({"http": row}))
    return rows


def run_sweep_binary(port, sample_shape, levels, duration):
    samples = _samples(sample_shape, n=32)
    _closed_loop_binary(port, samples, 2, 0.3)  # warm-up
    rows = []
    shm = False
    for clients in levels:
        lat, errors, elapsed, used = _closed_loop_binary(
            port, samples, clients, duration)
        shm = shm or used
        row = _row(clients, lat, errors, elapsed)
        rows.append(row)
        print(json.dumps({"binary": row}))
    return rows, shm


def find_knee(rows, gain_floor=1.10):
    """The knee row: the last sweep level whose throughput still beat
    the previous level by >= ``gain_floor`` — past it, extra offered
    load only buys queueing latency."""
    knee = rows[0]
    for prev, row in zip(rows, rows[1:]):
        if row["throughput_rps"] >= prev["throughput_rps"] * gain_floor:
            knee = row
        else:
            break
    return knee


def sequential_baseline(engine, sample_shape, duration):
    """In-process single-sample loop through the same AOT engine: the
    no-batching reference the knee-throughput delta is quoted against."""
    from veles_tpu.observe.metrics import percentiles
    rng = numpy.random.RandomState(9)
    xs = rng.rand(64, *sample_shape).astype(numpy.float32)
    lat = []
    stop_at = time.perf_counter() + duration
    n = 0
    while time.perf_counter() < stop_at:
        t0 = time.perf_counter()
        engine.infer(xs[n % len(xs)])
        lat.append(time.perf_counter() - t0)
        n += 1
    ps = percentiles(lat)
    return {"requests_per_sec": round(len(lat) / duration, 1),
            **{p: round(v * 1e3, 3) for p, v in ps.items()}}


# -- the replica-fleet section ------------------------------------------------


def _emulate_device(engine, ms):
    """Pad every dispatch to ``ms`` of device time: the REAL engine
    still runs (and its host sync happens inside the pad, so results
    stay bit-identical); the remainder is slept GIL-free — a fixed
    per-chip step latency, which is what a real accelerator gives each
    replica and the 2-core CPU host cannot."""
    real_run = engine.run

    def run(x_dev, rung):
        t0 = time.perf_counter()
        out = real_run(x_dev, rung)
        numpy.asarray(out)
        rest = ms / 1e3 - (time.perf_counter() - t0)
        if rest > 0:
            time.sleep(rest)
        return out

    engine.run = run


def measure_compute_ceiling(duration=1.5):
    """The honest context number: aggregate dispatch rate of TWO real
    engines on TWO devices running concurrently vs one — on this CPU
    host XLA's shared thread pool caps it near 1x, which is WHY the
    fleet sweep emulates per-chip device time."""
    from veles_tpu.backends import Device
    from veles_tpu.serve import AOTEngine

    plans, params, sample_shape = _model_spec()
    engines = []
    for i in range(2):
        eng = AOTEngine(plans, params, sample_shape, ladder=(32,),
                        device=Device(backend="cpu", device_index=i))
        eng.compile()
        engines.append(eng)
    x = numpy.random.RandomState(3).rand(
        32, *sample_shape).astype(numpy.float32)
    xd = [eng.device.put(x) for eng in engines]

    def loop(eng, x_dev, out):
        n = 0
        stop_at = time.perf_counter() + duration
        while time.perf_counter() < stop_at:
            numpy.asarray(eng.run(x_dev, 32))
            n += 1
        out.append(n)

    warm = []
    loop(engines[0], xd[0], warm)
    one = []
    t0 = time.perf_counter()
    loop(engines[0], xd[0], one)
    one_rate = one[0] / (time.perf_counter() - t0)
    both = []
    threads = [threading.Thread(target=loop,
                                args=(engines[i], xd[i], both))
               for i in range(2)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    both_rate = sum(both) / (time.perf_counter() - t0)
    return {
        "one_engine_batches_per_s": round(one_rate, 1),
        "two_engines_batches_per_s": round(both_rate, 1),
        "concurrent_compute_scaling_x": round(both_rate / one_rate, 2),
    }


def run_fleet_sweep(replica_counts, levels, duration, emulate_ms,
                    max_delay_ms):
    """Aggregate-rps knee per replica count at the latency-optimal
    dispatch rung (ladder pinned to 8: the TPU-paper regime where
    throughput must come from more chips, not bigger batches), plus
    the REAL-engine bit-identity receipt across replicas."""
    from veles_tpu.serve import ReplicaPool

    plans, params, sample_shape = _model_spec()
    samples = _samples(sample_shape)

    # bit-identity first, with REAL engines (no emulation): every
    # replica must serve the exact bits of the single-replica path
    pool = ReplicaPool(plans, params, sample_shape,
                       replicas=max(replica_counts), ladder=(8,),
                       max_delay_s=max_delay_ms / 1e3, max_queue=4096)
    pool.compile()
    pool.start()
    probe = numpy.stack(samples[:8])
    try:
        reference = pool.engine.infer(probe)
        bit_identical = all(
            bool((numpy.stack([rep.batcher.infer(probe[i])
                               for i in range(len(probe))])
                  == reference).all())
            for rep in pool.replicas)
    finally:
        pool.stop()

    fleet = []
    for count in replica_counts:
        pool = ReplicaPool(plans, params, sample_shape,
                           replicas=count, ladder=(8,),
                           max_delay_s=max_delay_ms / 1e3,
                           max_queue=4096)
        pool.compile()
        if emulate_ms > 0:
            for rep in pool.replicas:
                _emulate_device(rep.engine, emulate_ms)
        pool.start()
        try:
            _closed_loop_inprocess(pool, samples, 2, 0.3)
            rows = []
            for clients in levels:
                row = _row(clients, *_closed_loop_inprocess(
                    pool, samples, clients, duration))
                rows.append(row)
                print(json.dumps({"fleet_replicas_%d" % count: row}))
            fleet.append({"replicas": count, "rows": rows,
                          "knee": find_knee(rows)})
        finally:
            pool.stop()
    base = fleet[0]["knee"]["throughput_rps"]
    for entry in fleet[1:]:
        entry["scaling_x_vs_single"] = round(
            entry["knee"]["throughput_rps"] / base, 2)
    return fleet, bit_identical


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--url", default=None,
                        help="existing /infer endpoint (default: "
                        "start an in-process service)")
    parser.add_argument("--quick", action="store_true",
                        help="CI-sized sweep (shorter levels)")
    parser.add_argument("--out", default="BENCH_serve.json")
    parser.add_argument("--duration", type=float, default=None,
                        help="seconds per sweep level")
    parser.add_argument("--max-delay-ms", type=float, default=2.0)
    parser.add_argument("--slo-p50-ms", type=float, default=50.0)
    parser.add_argument("--slo-p99-ms", type=float, default=200.0)
    parser.add_argument("--replicas", type=int, default=4,
                        help="largest replica count in the fleet sweep")
    parser.add_argument("--emulate-device-ms", type=float, default=5.0,
                        help="per-chip dispatch latency the fleet "
                        "sweep emulates (0 = real engines only; see "
                        "module docstring for why the CPU harness "
                        "needs this)")
    args = parser.parse_args(argv)

    _ensure_virtual_devices(max(args.replicas, 2))

    levels = [1, 2, 4, 8, 16, 32] if args.quick else \
        [1, 2, 4, 8, 16, 32, 64]
    wire_levels = levels[:4] if args.quick else levels[:5]
    fleet_levels = [8, 16, 32] if args.quick else [8, 16, 32, 64, 128]
    replica_counts = sorted({1, 2, args.replicas})
    duration = args.duration or (1.0 if args.quick else 3.0)
    ladder = (1, 8, 32, 128)

    service, pool, receipt, sample_shape = _build_service(
        ladder, args.max_delay_ms, args.slo_p50_ms, args.slo_p99_ms)
    url = args.url or "http://127.0.0.1:%d/infer" % service.port
    try:
        # headline: the batcher under in-process closed-loop load
        rows = run_sweep_inprocess(service.batcher, sample_shape,
                                   levels, duration)
        knee = find_knee(rows)
        sequential = sequential_baseline(pool.engine, sample_shape,
                                         duration)
        # transport characterization: same service, both wire fronts.
        # With --url the JSON rows measure an EXTERNAL server whose
        # binary port we do not know — a local binary sweep would A/B
        # two different servers, so it is skipped and the record says
        # so instead of publishing a meaningless ratio.
        http_rows = run_sweep_http(url, sample_shape, wire_levels,
                                   duration)
        from veles_tpu.serve import serve_snapshot
        if args.url:
            binary_rows = []
            transport_ab = {
                "note": "--url targets an external JSON front; the "
                        "binary sweep and the json-vs-binary A/B "
                        "need both fronts of ONE server and were "
                        "skipped"}
        else:
            binary_rows, shm = run_sweep_binary(
                service.transport_port, sample_shape, wire_levels,
                duration)
            http_knee = find_knee(http_rows)
            binary_knee = find_knee(binary_rows)
            transport_ab = {
                "http_knee": http_knee,
                "binary_knee": binary_knee,
                "binary_vs_http_rps_x": round(
                    binary_knee["throughput_rps"]
                    / http_knee["throughput_rps"], 2),
                "http_minus_binary_p50_ms": round(
                    http_knee["p50"] - binary_knee["p50"], 3),
                "binary_shm_bypass": shm,
            }
        print("transport a/b: %s" % json.dumps(transport_ab))
    finally:
        service.stop()

    fleet, bit_identical = run_fleet_sweep(
        replica_counts, fleet_levels, duration,
        args.emulate_device_ms, args.max_delay_ms)
    ceiling = measure_compute_ceiling()
    print("fleet: %s" % json.dumps(
        [{k: e[k] for k in ("replicas",) if k in e} |
         {"knee_rps": e["knee"]["throughput_rps"],
          "scaling": e.get("scaling_x_vs_single")} for e in fleet]))
    print("compute ceiling: %s" % json.dumps(ceiling))

    record = {
        "kind": "serve_bench",
        "schema": 2,
        "framing": "closed-loop latency-bound sweep; percentiles "
                   "are the headline (TPU in-datacenter paper), "
                   "throughput is reported AT the latency knee",
        "model": "mlp_784_256_10_random_params",
        "ladder": list(ladder),
        "max_delay_ms": args.max_delay_ms,
        "duration_per_level_s": duration,
        "rows": rows,
        "knee": knee,
        "sequential_single_sample": sequential,
        "batched_vs_sequential_x": round(
            knee["throughput_rps"]
            / sequential["requests_per_sec"], 2),
        "http_rows": http_rows,
        "binary_rows": binary_rows,
        "transport_ab": transport_ab,
        "transport_note": "json and binary rows drive the SAME "
                          "service/batcher; the delta is pure "
                          "transport (tornado+json text vs length-"
                          "prefixed raw tensor frames with same-host "
                          "shm payload bypass)",
        "fleet": {
            "ladder": [8],
            "emulated_device_ms": args.emulate_device_ms,
            "levels": fleet_levels,
            "per_replica_bit_identical": bit_identical,
            "sweeps": fleet,
            "cpu_compute_ceiling": ceiling,
            "note": "fixed latency-optimal rung (8): the TPU-paper "
                    "regime where aggregate rps must come from more "
                    "chips.  Dispatches run the real engines, padded "
                    "to emulated_device_ms of per-chip device time "
                    "because this host cannot co-run N compute "
                    "streams (see cpu_compute_ceiling: two real "
                    "engines concurrently reach only ~1.3x one); "
                    "real-chip fleet receipts remain a ROADMAP item",
        },
        "compile_receipt": receipt,
        "serve_health_at_end": serve_snapshot() or None,
    }
    with open(args.out, "w") as fout:
        json.dump(record, fout, indent=1)
    print("knee: %s" % json.dumps(knee))
    print("sequential: %s  batched-vs-sequential at knee: %.2fx"
          % (json.dumps(sequential),
             record["batched_vs_sequential_x"]))
    print("wrote %s" % args.out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
