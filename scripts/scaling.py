"""Scaling-efficiency harness (BASELINE target: >= 70 % at 8 -> 64
chips, grad-merge -> ICI psum).

Two parts:

1. MEASURE: runs the fused data-parallel train step on 1..8 devices at
   fixed per-device batch (weak scaling), recording step wall time and
   the collective traffic the compiled program actually issues (summed
   from all-reduce ops in the optimized HLO).  On this host the devices
   are XLA virtual CPU devices, so the times validate *semantics and
   collective volume*, not ICI speed; run unmodified on a real pod
   (it detects >= 2 real TPU devices) to measure real step times.

2. PROJECT: an analytic ICI model — ring all-reduce over the data axis,
   t_comm(n) = 2 (n-1)/n * grad_bytes / ici_bw + (n-1) * hop_latency,
   no overlap credited (conservative: XLA overlaps grad all-reduce with
   the tail of the backward pass) — combined with the single-chip step
   time measured by bench.py on the real chip, yields projected
   efficiency at 8/16/32/64 chips.

   Model constants (documented, overridable by flags): v5e ICI
   2D torus, 1600 Gbit/s aggregate per chip -> ~100 GB/s usable per
   all-reduce direction; 1 us per hop launch latency.

    python scripts/scaling.py [--out SCALING.json]
"""

import argparse
import json
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# one worker invocation per device count: the XLA device count is fixed
# at backend init, so each measurement needs a fresh interpreter
_WORKER = r"""
import json, os, re, sys, time
sys.path.insert(0, %(repo)r)
if os.environ.get("VELES_SCALING_CPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
import jax
if os.environ.get("VELES_SCALING_CPU"):
    jax.config.update("jax_platforms", "cpu")
import numpy
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from veles_tpu.compiler import build_train_step
from veles_tpu.models.zoo import alexnet_layers, build_plans_and_state
from veles_tpu.parallel import make_mesh

n = %(n)d
per_device_batch = %(pdb)d
size = %(size)d
devices = jax.devices()[:n]
mesh = make_mesh({"data": n}, devices)

specs = alexnet_layers(classes=10)
plans, state, _ = build_plans_and_state(specs, (size, size, 3), seed=1)

repl = NamedSharding(mesh, P())
bsh = NamedSharding(mesh, P("data"))
state_sh = jax.tree.map(lambda leaf: repl, state,
                        is_leaf=lambda x: x is None)
state_sh = jax.tree.map(
    lambda leaf, sh: None if leaf is None else sh, state, state_sh,
    is_leaf=lambda x: x is None)

step = build_train_step(plans, mesh=mesh, data_axis="data",
                        state_shardings=state_sh, batch_sharding=bsh,
                        donate=False)

batch = per_device_batch * n
rng = numpy.random.RandomState(0)
x = jax.device_put(rng.rand(batch, size, size, 3).astype(numpy.float32),
                   bsh)
y = jax.device_put(rng.randint(0, 10, batch).astype(numpy.int32), bsh)
state = jax.tree.map(
    lambda leaf, sh: None if leaf is None else jax.device_put(leaf, sh),
    state, state_sh, is_leaf=lambda v: v is None)

import jax.random as jrandom
key = jrandom.PRNGKey(0)
lowered = jax.jit(step).lower(state, x, y, numpy.float32(batch), key)
compiled = lowered.compile()
hlo = compiled.as_text()

from veles_tpu.parallel.analysis import parse_collective_bytes
total = parse_collective_bytes(hlo)["all-reduce"]

s2, metrics = step(state, x, y, numpy.float32(batch), key)
jax.block_until_ready(s2)

def chain(k):
    t0 = time.perf_counter()
    s = state
    m = None
    for i in range(k):
        s, m = step(s, x, y, numpy.float32(batch), key)
    float(m["loss"])
    return time.perf_counter() - t0

best = float("inf")
for _ in range(2):
    t1, t2 = chain(1), chain(4)
    best = min(best, (t2 - t1) / 3)
print(json.dumps({"n": n, "batch": batch,
                  "step_seconds": max(best, 1e-9),
                  "allreduce_bytes": total}))
"""


def measure(device_counts, per_device_batch, size):
    results = []
    on_real_pod = False
    try:
        import jax
        on_real_pod = (len(jax.devices()) >= 2 and
                       jax.devices()[0].platform == "tpu")
    except Exception:
        pass
    for n in device_counts:
        env = dict(os.environ)
        if not on_real_pod:
            env["VELES_SCALING_CPU"] = "1"
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "") +
                " --xla_force_host_platform_device_count=%d" % n).strip()
            env["VELES_BACKEND"] = "cpu"
        body = _WORKER % {"repo": REPO, "n": n,
                          "pdb": per_device_batch, "size": size}
        proc = subprocess.run([sys.executable, "-c", body], env=env,
                              capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError("worker n=%d failed:\n%s" %
                               (n, proc.stderr[-2000:]))
        results.append(json.loads(proc.stdout.strip().splitlines()[-1]))
    return results, on_real_pod


def project(step_seconds_1chip, grad_bytes, ici_gbps=100.0,
            hop_latency_s=1e-6, counts=(8, 16, 32, 64)):
    """Ring all-reduce model, no overlap credited."""
    out = {}
    bw = ici_gbps * 1e9
    for n in counts:
        t_comm = 2.0 * (n - 1) / n * grad_bytes / bw + \
            (n - 1) * hop_latency_s
        t_step = step_seconds_1chip + t_comm
        out[str(n)] = {
            "t_comm_ms": round(t_comm * 1e3, 4),
            "t_step_ms": round(t_step * 1e3, 4),
            "efficiency_pct": round(
                100.0 * step_seconds_1chip / t_step, 2),
        }
    return out


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=os.path.join(REPO,
                                                      "SCALING.json"))
    parser.add_argument("--per-device-batch", type=int, default=8)
    parser.add_argument("--size", type=int, default=67,
                        help="input image side (67 keeps CPU runs fast; "
                             "use 227 on a real pod)")
    parser.add_argument("--counts", default="1,2,4,8")
    parser.add_argument("--ici-gbps", type=float, default=100.0,
                        help="usable all-reduce bandwidth GB/s per chip "
                             "(v5e 2D-torus derated)")
    parser.add_argument("--step-seconds", type=float, default=None,
                        help="single-chip step time from bench.py "
                             "(defaults to BENCH extras if present)")
    args = parser.parse_args()

    counts = [int(c) for c in args.counts.split(",")]
    measured, on_real_pod = measure(counts, args.per_device_batch,
                                    args.size)

    grad_bytes = measured[-1]["allreduce_bytes"]
    step_1 = args.step_seconds
    source = "flag"
    if step_1 is None:
        # prefer the real-chip AlexNet step from the bench extras
        for bench_file in ("BENCH_r02.json", "BENCH_local.json"):
            path = os.path.join(REPO, bench_file)
            if os.path.exists(path):
                try:
                    parsed = json.load(open(path))
                    parsed = parsed.get("parsed", parsed)
                    step_1 = parsed["extras"]["alexnet"]["float32"][
                        "step_seconds"]
                    source = bench_file
                    break
                except (KeyError, ValueError, TypeError):
                    continue
    if step_1 is None:
        step_1 = measured[0]["step_seconds"]
        source = "cpu-measured (NOT TPU-representative)"

    report = {
        "measured": measured,
        "measured_on": "real tpu pod" if on_real_pod
        else "virtual cpu devices (semantics + collective bytes only)",
        "allreduce_bytes_per_step": grad_bytes,
        "model": {
            "kind": "ring all-reduce, no overlap credited",
            "ici_usable_gbps": args.ici_gbps,
            "hop_latency_s": 1e-6,
            "single_chip_step_seconds": step_1,
            "step_seconds_source": source,
        },
        "projection": project(step_1, grad_bytes,
                              ici_gbps=args.ici_gbps),
        "target": {"efficiency_pct_8_to_64": 70.0,
                   "source": "BASELINE.md"},
    }
    # the 8->64 headline: efficiency(64) relative to efficiency(8)
    e8 = report["projection"]["8"]["efficiency_pct"]
    e64 = report["projection"]["64"]["efficiency_pct"]
    report["projected_8_to_64_relative_pct"] = round(100.0 * e64 / e8, 2)

    with open(args.out, "w") as fout:
        json.dump(report, fout, indent=1, sort_keys=True)
        fout.write("\n")
    print(json.dumps({"scaling_8_to_64_relative_pct":
                      report["projected_8_to_64_relative_pct"],
                      "out": args.out}))


if __name__ == "__main__":
    main()
