"""Scaling-efficiency harness (BASELINE target: >= 70 % at 8 -> 64
chips, grad-merge -> ICI psum).

Two parts, internally consistent (round-2 verdict: bytes and step time
must describe the SAME network):

1. COLLECTIVE BYTES: lowers the data-parallel train step of the FULL
   AlexNet (227 px, 1000 classes — the exact model bench.py times on
   the real chip) over 2..64 virtual devices and sums the all-reduce
   payload the optimized HLO actually issues.  Since PR 6 this covers
   BOTH planes: the flat pjit-annotation step (one fused ~250 MB
   all-reduce) and the SPMD bucketed step
   (compiler.build_train_step(grad_bucket_mb=...)), whose optimized
   HLO is audited per-op — one all-reduce per bucket, sizes recorded —
   so a silent regression to the flat monolith is visible in the
   receipt.  Compile-only: no execution, so the full model is
   tractable on a CPU host and no misleading oversubscribed step times
   are recorded.  On a host with >= 2 real TPU chips the step is also
   executed and real step times recorded.

2. PROJECT: the analytic ICI ring model, now OVERLAP-CREDITED
   (veles_tpu.parallel.bucketed.overlap_model): bucket k's all-reduce
   hides behind the backward compute that produces buckets k+1.., up
   to the measured bucket granularity; the last bucket plus per-bucket
   hop latency stay exposed.  The old no-overlap projection is kept in
   the report as "projection_no_overlap" for comparison.  Combined
   with the single-chip step time measured by bench.py on the real
   chip, this yields projected efficiency at 8/16/32/64 chips plus a
   bandwidth/latency sensitivity table.

   Model constants (documented, overridable by flags): v5e ICI
   2D torus, 1600 Gbit/s aggregate per chip -> ~100 GB/s usable per
   all-reduce direction; 1 us per hop launch latency; backward
   fraction 0.6 of the step (MFU.json round-5 attribution).

    python scripts/scaling.py [--out SCALING.json]
                              [--multichip-out MULTICHIP_rNN.json]
"""

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# one worker invocation per device count: the XLA device count is fixed
# at backend init, so each measurement needs a fresh interpreter
_WORKER = r"""
import json, os, sys, time
sys.path.insert(0, %(repo)r)
if os.environ.get("VELES_SCALING_CPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
import jax
if os.environ.get("VELES_SCALING_CPU"):
    jax.config.update("jax_platforms", "cpu")
import numpy
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from veles_tpu.compiler import build_train_step
from veles_tpu.models.zoo import alexnet_layers, build_plans_and_state
from veles_tpu.parallel import make_mesh

n = %(n)d
per_device_batch = %(pdb)d
size = %(size)d
classes = %(classes)d
execute = %(execute)d
bucket_mb = %(bucket_mb)r
devices = jax.devices()[:n]
mesh = make_mesh({"data": n}, devices)

specs = alexnet_layers(classes=classes)
plans, state, _ = build_plans_and_state(specs, (size, size, 3), seed=1)

repl = NamedSharding(mesh, P())
bsh = NamedSharding(mesh, P("data"))
state_sh = jax.tree.map(
    lambda leaf: None if leaf is None else repl, state,
    is_leaf=lambda x: x is None)

step = build_train_step(plans, mesh=mesh, data_axis="data",
                        state_shardings=state_sh, batch_sharding=bsh,
                        donate=False)

batch = per_device_batch * n
# gradient payload = one float per trainable parameter (weights/bias)
grad_bytes_analytic = sum(
    int(numpy.prod(layer[key].shape)) * 4
    for layer in state for key in ("weights", "bias")
    if layer.get(key) is not None)

state = jax.tree.map(
    lambda leaf, sh: None if leaf is None else jax.device_put(leaf, sh),
    state, state_sh, is_leaf=lambda v: v is None)
import jax.random as jrandom
key = jrandom.PRNGKey(0)
# abstract batch avoids materializing a 64-device global batch on CPU
x = jax.ShapeDtypeStruct((batch, size, size, 3), jnp.float32,
                         sharding=bsh)
y = jax.ShapeDtypeStruct((batch,), jnp.int32, sharding=bsh)

lowered = jax.jit(step).lower(state, x, y, numpy.float32(batch), key)
compiled = lowered.compile()
hlo = compiled.as_text()

from veles_tpu.parallel.analysis import (parse_collective_bytes,
                                         parse_collective_ops)
total = parse_collective_bytes(hlo)["all-reduce"]

out = {"n": n, "batch": batch, "allreduce_bytes": total,
       "grad_bytes_analytic": grad_bytes_analytic}

if bucket_mb is not None:
    # the SPMD bucketed plane, audited per-op: the optimized HLO must
    # carry ONE all-reduce per bucket (metric psums are the few-byte
    # stragglers) or the overlap schedule silently regressed to flat
    step_b = build_train_step(plans, mesh=mesh, data_axis="data",
                              grad_bucket_mb=bucket_mb, donate=False)
    hlo_b = step_b.lower(state, x, y, numpy.float32(batch),
                         None).compile().as_text()
    ops = [op["bytes"] for op in parse_collective_ops(hlo_b)
           if op["kind"] == "all-reduce"]
    grad_ops = [b for b in ops if b >= 1024]
    out["bucketed"] = {
        "bucket_mb": bucket_mb,
        "allreduce_ops": len(ops),
        "grad_bucket_ops": len(grad_ops),
        "grad_bucket_bytes": grad_ops,
        "allreduce_bytes": sum(ops),
    }

if execute:
    xr = jax.device_put(numpy.random.RandomState(0).rand(
        batch, size, size, 3).astype(numpy.float32), bsh)
    yr = jax.device_put(numpy.random.RandomState(0).randint(
        0, classes, batch).astype(numpy.int32), bsh)
    s2, metrics = step(state, xr, yr, numpy.float32(batch), key)
    jax.block_until_ready(s2)

    def chain(k):
        t0 = time.perf_counter()
        s = state
        m = None
        for i in range(k):
            s, m = step(s, xr, yr, numpy.float32(batch), key)
        float(m["loss"])
        return time.perf_counter() - t0

    best = float("inf")
    for _ in range(2):
        t1, t2 = chain(1), chain(5)
        best = min(best, (t2 - t1) / 4)
    if best <= 0:
        out["step_seconds_error"] = "non-positive slope %%r" %% best
    else:
        out["step_seconds"] = best
print(json.dumps(out))
"""


def measure(device_counts, per_device_batch, size, classes,
            bucket_mb=None, bucket_counts=()):
    """One fresh-interpreter worker per device count.  Counts listed
    in ``bucket_counts`` additionally lower the SPMD bucketed step
    (an extra full-model compile each, so the per-bucket audit runs
    at representative counts instead of all of them)."""
    results = []
    on_real_pod = False
    try:
        import jax
        on_real_pod = (len(jax.devices()) >= 2 and
                       jax.devices()[0].platform == "tpu")
    except Exception:
        pass
    if on_real_pod:
        # a real pod cannot be resized: keep counts the hardware can
        # serve, and prepend n=1 so a true single-chip step time
        # exists to seed the projection
        import jax
        avail = len(jax.devices())
        device_counts = [1] + [c for c in device_counts
                               if 1 < c <= avail]
    for n in device_counts:
        env = dict(os.environ)
        if not on_real_pod:
            env["VELES_SCALING_CPU"] = "1"
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "") +
                " --xla_force_host_platform_device_count=%d" % n).strip()
            env["VELES_BACKEND"] = "cpu"
        body = _WORKER % {"repo": REPO, "n": n,
                          "pdb": per_device_batch, "size": size,
                          "classes": classes,
                          "bucket_mb": (bucket_mb if n in bucket_counts
                                        else None),
                          "execute": 1 if on_real_pod else 0}
        proc = subprocess.run([sys.executable, "-c", body], env=env,
                              capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError("worker n=%d failed:\n%s" %
                               (n, proc.stderr[-2000:]))
        results.append(json.loads(proc.stdout.strip().splitlines()[-1]))
    return results, on_real_pod


def project(step_seconds_1chip, grad_bytes, ici_gbps=100.0,
            hop_latency_s=1e-6, counts=(8, 16, 32, 64)):
    """Ring all-reduce model, no overlap credited (the pre-PR 6
    reference projection, kept for comparison)."""
    out = {}
    bw = ici_gbps * 1e9
    for n in counts:
        t_comm = 2.0 * (n - 1) / n * grad_bytes / bw + \
            (n - 1) * hop_latency_s
        t_step = step_seconds_1chip + t_comm
        out[str(n)] = {
            "t_comm_ms": round(t_comm * 1e3, 4),
            "t_step_ms": round(t_step * 1e3, 4),
            "efficiency_pct": round(
                100.0 * step_seconds_1chip / t_step, 2),
        }
    return out


def project_overlap(step_seconds_1chip, grad_bytes, n_buckets,
                    ici_gbps=100.0, hop_latency_s=1e-6,
                    bwd_fraction=0.6, counts=(8, 16, 32, 64)):
    """Overlap-credited projection: the bucketed all-reduce hides
    behind the backward up to the measured bucket granularity
    (veles_tpu.parallel.bucketed.overlap_model — the SAME model the
    live ``comm.overlap_pct`` gauge publishes)."""
    from veles_tpu.parallel.bucketed import overlap_model
    out = {}
    for n in counts:
        model = overlap_model(
            grad_bytes, n_buckets, n, step_seconds=step_seconds_1chip,
            ici_gbps=ici_gbps, hop_latency_s=hop_latency_s,
            bwd_fraction=bwd_fraction)
        t_step = step_seconds_1chip + model["t_comm_exposed_s"]
        out[str(n)] = {
            "t_comm_ms": round(model["t_comm_s"] * 1e3, 4),
            "t_comm_exposed_ms": round(
                model["t_comm_exposed_s"] * 1e3, 4),
            "overlap_pct": model["overlap_pct"],
            "t_step_ms": round(t_step * 1e3, 4),
            "efficiency_pct": round(
                100.0 * step_seconds_1chip / t_step, 2),
        }
    return out


def _bench_step_seconds():
    """Single-chip AlexNet f32 step time from the newest plausible
    bench record (skips records with clamped/failed measurements)."""
    for bench_file in ("BENCH_r03.json", "BENCH_local.json",
                       "BENCH_r02.json"):
        path = os.path.join(REPO, bench_file)
        if not os.path.exists(path):
            continue
        try:
            parsed = json.load(open(path))
            parsed = parsed.get("parsed", parsed)
            step = parsed["extras"]["alexnet"]["float32"]["step_seconds"]
        except (KeyError, ValueError, TypeError):
            continue
        # a real 227px AlexNet step cannot run in under 100 us or over
        # 10 s on any current chip — reject corrupt records (round-2
        # lesson: BENCH_r02 carried a floor-clamped 1e-9)
        if 1e-4 < step < 10.0:
            return step, bench_file
    return None, None


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=os.path.join(REPO,
                                                      "SCALING.json"))
    parser.add_argument("--per-device-batch", type=int, default=128,
                        help="matches the bench.py single-chip batch "
                             "so t_step and t_comm describe one run")
    parser.add_argument("--size", type=int, default=227)
    parser.add_argument("--classes", type=int, default=1000)
    parser.add_argument("--counts", default="2,4,8,16,32,64")
    parser.add_argument("--ici-gbps", type=float, default=100.0,
                        help="usable all-reduce bandwidth GB/s per chip "
                             "(v5e 2D-torus derated)")
    parser.add_argument("--step-seconds", type=float, default=None,
                        help="single-chip step time from bench.py "
                             "(defaults to BENCH extras if present)")
    parser.add_argument("--grad-bucket-mb", type=float, default=25.0,
                        help="bucket size target for the SPMD plane's "
                             "per-op collective audit + overlap model")
    parser.add_argument("--bucket-counts", default="8,64",
                        help="device counts at which the bucketed SPMD "
                             "step is additionally lowered and audited "
                             "per-op (each costs a full-model compile)")
    parser.add_argument("--bwd-fraction", type=float, default=0.6,
                        help="fraction of the step the backward+update "
                             "occupies (MFU.json round-5 attribution); "
                             "sizes the overlap window")
    parser.add_argument("--multichip-out", default=None, metavar="PATH",
                        help="also write a MULTICHIP-style weak-scaling "
                             "receipt (rows past n=8) to PATH")
    args = parser.parse_args()

    counts = [int(c) for c in args.counts.split(",")]
    bucket_counts = {int(c) for c in args.bucket_counts.split(",") if c}
    measured, on_real_pod = measure(counts, args.per_device_batch,
                                    args.size, args.classes,
                                    bucket_mb=args.grad_bucket_mb,
                                    bucket_counts=bucket_counts)

    flat_bytes = measured[-1]["allreduce_bytes"]
    analytic = measured[-1]["grad_bytes_analytic"]
    # the projection models the SPMD bucketed plane, so its byte input
    # is that plane's measured gradient traffic (exactly the gradient
    # pytree: the per-bucket ops sum to it).  The pjit annotation path
    # is kept as a reference — the current toolchain's optimized HLO
    # issues ~2x the gradient bytes there (extra backward
    # re-reductions), which is itself a receipt FOR the explicit plane.
    audited_pre = [m for m in measured if m.get("bucketed")]
    if audited_pre:
        grad_bytes = sum(
            audited_pre[-1]["bucketed"]["grad_bucket_bytes"])
    else:
        grad_bytes = flat_bytes
    step_1 = args.step_seconds
    source = "flag"
    if step_1 is None:
        step_1, source = _bench_step_seconds()
    if step_1 is None:
        # only a TRUE single-chip row can seed the projection — an
        # n>=2 step time already contains all-reduce comm and would
        # double-count t_comm
        single = next((m for m in measured
                       if m["n"] == 1 and "step_seconds" in m), None)
        if on_real_pod and single:
            step_1 = single["step_seconds"]
            source = "measured on this pod (n=1)"
        else:
            sys.stderr.write(
                "ERROR: no trustworthy single-chip step time: no "
                "plausible BENCH_*.json record found and this host has "
                "no real TPU pod.  Pass --step-seconds from a real-chip "
                "bench run; refusing to project from oversubscribed-CPU "
                "times (they are not TPU-representative).\n")
            raise SystemExit(2)

    # measured bucket granularity: the per-op audit of the LARGEST
    # bucketed lowering (falls back to the analytic plan size if no
    # count was audited)
    audited = audited_pre
    if audited:
        n_buckets = audited[-1]["bucketed"]["grad_bucket_ops"]
        buckets_source = "measured HLO ops at n=%d" % audited[-1]["n"]
    else:
        n_buckets = max(
            int(-(-grad_bytes // (args.grad_bucket_mb * 2 ** 20))), 1)
        buckets_source = "analytic (no bucketed lowering ran)"

    projection = project_overlap(
        step_1, grad_bytes, n_buckets, ici_gbps=args.ici_gbps,
        bwd_fraction=args.bwd_fraction)
    projection_no_overlap = project(step_1, grad_bytes,
                                    ici_gbps=args.ici_gbps)

    report = {
        "measured": measured,
        "measured_on": "real tpu pod" if on_real_pod
        else ("virtual cpu devices, compile-only "
              "(collective bytes; no step times — oversubscribed-CPU "
              "times are not TPU-representative)"),
        "model_config": {"size": args.size, "classes": args.classes,
                         "per_device_batch": args.per_device_batch},
        "allreduce_bytes_per_step": grad_bytes,
        "allreduce_bytes_per_step_flat_pjit": flat_bytes,
        "grad_pytree_bytes_analytic": analytic,
        "model": {
            "kind": "ring all-reduce, overlap-credited (bucketed, "
                    "parallel/bucketed.overlap_model)",
            "ici_usable_gbps": args.ici_gbps,
            "hop_latency_s": 1e-6,
            "grad_bucket_mb": args.grad_bucket_mb,
            "n_buckets": n_buckets,
            "n_buckets_source": buckets_source,
            "bwd_fraction": args.bwd_fraction,
            "single_chip_step_seconds": step_1,
            "step_seconds_source": source,
        },
        "projection": projection,
        "projection_no_overlap": projection_no_overlap,
        "sensitivity_at_64": {
            "bw_%.0fgbps_hop_%.0fus" % (gbps, hop * 1e6):
            project_overlap(
                step_1, grad_bytes, n_buckets, ici_gbps=gbps,
                hop_latency_s=hop, bwd_fraction=args.bwd_fraction,
                counts=(64,))["64"]["efficiency_pct"]
            for gbps in (args.ici_gbps / 2, args.ici_gbps,
                         args.ici_gbps * 2)
            for hop in (1e-6, 5e-6)
        },
        "target": {"efficiency_pct_8_to_64": 70.0,
                   "source": "BASELINE.md"},
    }
    # the 8->64 headline: efficiency(64) relative to efficiency(8)
    e8 = report["projection"]["8"]["efficiency_pct"]
    e64 = report["projection"]["64"]["efficiency_pct"]
    report["projected_8_to_64_relative_pct"] = round(100.0 * e64 / e8, 2)
    e8n = projection_no_overlap["8"]["efficiency_pct"]
    e64n = projection_no_overlap["64"]["efficiency_pct"]
    report["projected_8_to_64_relative_pct_no_overlap"] = round(
        100.0 * e64n / e8n, 2)
    report["headline_note"] = (
        "overlap crediting improves ABSOLUTE efficiency at every "
        "count (8 chips: %.2f%% vs %.2f%% no-overlap; 64 chips: "
        "%.2f%% vs %.2f%%).  The 8->64 RELATIVE ratio can still read "
        "lower than the no-overlap ratio because overlap helps the "
        "8-chip baseline the most (its comm hides almost entirely); "
        "a ratio of two efficiencies penalizes improving the "
        "denominator — judge the absolute rows."
        % (e8, e8n, e64, e64n))

    with open(args.out, "w") as fout:
        json.dump(report, fout, indent=1, sort_keys=True)
        fout.write("\n")

    if args.multichip_out:
        # weak-scaling receipt rows past n=8 (per-device batch fixed,
        # global batch grows with n): measured collective bytes per
        # step + the overlap-credited efficiency at each count
        rows = []
        for m in measured:
            n = m["n"]
            row = {"n_devices": n, "batch": m["batch"],
                   "allreduce_bytes": m["allreduce_bytes"],
                   "weak_scaling_efficiency_pct":
                   projection.get(str(n), {}).get("efficiency_pct"),
                   "overlap_pct":
                   projection.get(str(n), {}).get("overlap_pct")}
            if m.get("bucketed"):
                row["grad_bucket_ops"] = m["bucketed"]["grad_bucket_ops"]
                row["grad_bucket_bytes"] = \
                    m["bucketed"]["grad_bucket_bytes"]
            rows.append(row)
        receipt = {"n_devices": max(m["n"] for m in measured),
                   "rc": 0, "ok": True, "skipped": False,
                   "kind": "weak scaling, SPMD bucketed data plane "
                           "(compile-only collective bytes + "
                           "overlap-credited model)",
                   "grad_bucket_mb": args.grad_bucket_mb,
                   "rows": rows, "tail": ""}
        with open(args.multichip_out, "w") as fout:
            json.dump(receipt, fout, indent=1, sort_keys=True)
            fout.write("\n")

    print(json.dumps({"scaling_8_to_64_relative_pct":
                      report["projected_8_to_64_relative_pct"],
                      "no_overlap_reference_pct":
                      report["projected_8_to_64_relative_pct_no_overlap"],
                      "absolute_efficiency_at_64_pct":
                      report["projection"]["64"]["efficiency_pct"],
                      "n_buckets": n_buckets,
                      "out": args.out}))


if __name__ == "__main__":
    main()
