"""Scaling-efficiency harness (BASELINE target: >= 70 % at 8 -> 64
chips, grad-merge -> ICI psum).

Two parts, now internally consistent (round-2 verdict: bytes and step
time must describe the SAME network):

1. COLLECTIVE BYTES: lowers the fused data-parallel train step of the
   FULL AlexNet (227 px, 1000 classes — the exact model bench.py times
   on the real chip) over 2..64 virtual devices and sums the all-reduce
   payload the optimized HLO actually issues.  Compile-only: no
   execution, so the full model is tractable on a CPU host and no
   misleading oversubscribed step times are recorded (the round-2
   report published 1->8 virtual-CPU times that *rose* 28x — real
   slowdown on an oversubscribed host, noise as a scaling signal).
   On a host with >= 2 real TPU chips the step is also executed and
   real step times recorded.

2. PROJECT: an analytic ICI model — ring all-reduce over the data axis,
   t_comm(n) = 2 (n-1)/n * grad_bytes / ici_bw + (n-1) * hop_latency,
   no overlap credited (conservative: XLA overlaps grad all-reduce with
   the tail of the backward pass) — combined with the single-chip step
   time measured by bench.py on the real chip, yields projected
   efficiency at 8/16/32/64 chips, plus a bandwidth/latency sensitivity
   table.

   Model constants (documented, overridable by flags): v5e ICI
   2D torus, 1600 Gbit/s aggregate per chip -> ~100 GB/s usable per
   all-reduce direction; 1 us per hop launch latency.

    python scripts/scaling.py [--out SCALING.json]
"""

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# one worker invocation per device count: the XLA device count is fixed
# at backend init, so each measurement needs a fresh interpreter
_WORKER = r"""
import json, os, sys, time
sys.path.insert(0, %(repo)r)
if os.environ.get("VELES_SCALING_CPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
import jax
if os.environ.get("VELES_SCALING_CPU"):
    jax.config.update("jax_platforms", "cpu")
import numpy
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from veles_tpu.compiler import build_train_step
from veles_tpu.models.zoo import alexnet_layers, build_plans_and_state
from veles_tpu.parallel import make_mesh

n = %(n)d
per_device_batch = %(pdb)d
size = %(size)d
classes = %(classes)d
execute = %(execute)d
devices = jax.devices()[:n]
mesh = make_mesh({"data": n}, devices)

specs = alexnet_layers(classes=classes)
plans, state, _ = build_plans_and_state(specs, (size, size, 3), seed=1)

repl = NamedSharding(mesh, P())
bsh = NamedSharding(mesh, P("data"))
state_sh = jax.tree.map(
    lambda leaf: None if leaf is None else repl, state,
    is_leaf=lambda x: x is None)

step = build_train_step(plans, mesh=mesh, data_axis="data",
                        state_shardings=state_sh, batch_sharding=bsh,
                        donate=False)

batch = per_device_batch * n
# gradient payload = one float per trainable parameter (weights/bias)
grad_bytes_analytic = sum(
    int(numpy.prod(layer[key].shape)) * 4
    for layer in state for key in ("weights", "bias")
    if layer.get(key) is not None)

state = jax.tree.map(
    lambda leaf, sh: None if leaf is None else jax.device_put(leaf, sh),
    state, state_sh, is_leaf=lambda v: v is None)
import jax.random as jrandom
key = jrandom.PRNGKey(0)
# abstract batch avoids materializing a 64-device global batch on CPU
x = jax.ShapeDtypeStruct((batch, size, size, 3), jnp.float32,
                         sharding=bsh)
y = jax.ShapeDtypeStruct((batch,), jnp.int32, sharding=bsh)

lowered = jax.jit(step).lower(state, x, y, numpy.float32(batch), key)
compiled = lowered.compile()
hlo = compiled.as_text()

from veles_tpu.parallel.analysis import parse_collective_bytes
total = parse_collective_bytes(hlo)["all-reduce"]

out = {"n": n, "batch": batch, "allreduce_bytes": total,
       "grad_bytes_analytic": grad_bytes_analytic}

if execute:
    xr = jax.device_put(numpy.random.RandomState(0).rand(
        batch, size, size, 3).astype(numpy.float32), bsh)
    yr = jax.device_put(numpy.random.RandomState(0).randint(
        0, classes, batch).astype(numpy.int32), bsh)
    s2, metrics = step(state, xr, yr, numpy.float32(batch), key)
    jax.block_until_ready(s2)

    def chain(k):
        t0 = time.perf_counter()
        s = state
        m = None
        for i in range(k):
            s, m = step(s, xr, yr, numpy.float32(batch), key)
        float(m["loss"])
        return time.perf_counter() - t0

    best = float("inf")
    for _ in range(2):
        t1, t2 = chain(1), chain(5)
        best = min(best, (t2 - t1) / 4)
    if best <= 0:
        out["step_seconds_error"] = "non-positive slope %%r" %% best
    else:
        out["step_seconds"] = best
print(json.dumps(out))
"""


def measure(device_counts, per_device_batch, size, classes):
    results = []
    on_real_pod = False
    try:
        import jax
        on_real_pod = (len(jax.devices()) >= 2 and
                       jax.devices()[0].platform == "tpu")
    except Exception:
        pass
    if on_real_pod:
        # a real pod cannot be resized: keep counts the hardware can
        # serve, and prepend n=1 so a true single-chip step time
        # exists to seed the projection
        import jax
        avail = len(jax.devices())
        device_counts = [1] + [c for c in device_counts
                               if 1 < c <= avail]
    for n in device_counts:
        env = dict(os.environ)
        if not on_real_pod:
            env["VELES_SCALING_CPU"] = "1"
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "") +
                " --xla_force_host_platform_device_count=%d" % n).strip()
            env["VELES_BACKEND"] = "cpu"
        body = _WORKER % {"repo": REPO, "n": n,
                          "pdb": per_device_batch, "size": size,
                          "classes": classes,
                          "execute": 1 if on_real_pod else 0}
        proc = subprocess.run([sys.executable, "-c", body], env=env,
                              capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError("worker n=%d failed:\n%s" %
                               (n, proc.stderr[-2000:]))
        results.append(json.loads(proc.stdout.strip().splitlines()[-1]))
    return results, on_real_pod


def project(step_seconds_1chip, grad_bytes, ici_gbps=100.0,
            hop_latency_s=1e-6, counts=(8, 16, 32, 64)):
    """Ring all-reduce model, no overlap credited."""
    out = {}
    bw = ici_gbps * 1e9
    for n in counts:
        t_comm = 2.0 * (n - 1) / n * grad_bytes / bw + \
            (n - 1) * hop_latency_s
        t_step = step_seconds_1chip + t_comm
        out[str(n)] = {
            "t_comm_ms": round(t_comm * 1e3, 4),
            "t_step_ms": round(t_step * 1e3, 4),
            "efficiency_pct": round(
                100.0 * step_seconds_1chip / t_step, 2),
        }
    return out


def _bench_step_seconds():
    """Single-chip AlexNet f32 step time from the newest plausible
    bench record (skips records with clamped/failed measurements)."""
    for bench_file in ("BENCH_r03.json", "BENCH_local.json",
                       "BENCH_r02.json"):
        path = os.path.join(REPO, bench_file)
        if not os.path.exists(path):
            continue
        try:
            parsed = json.load(open(path))
            parsed = parsed.get("parsed", parsed)
            step = parsed["extras"]["alexnet"]["float32"]["step_seconds"]
        except (KeyError, ValueError, TypeError):
            continue
        # a real 227px AlexNet step cannot run in under 100 us or over
        # 10 s on any current chip — reject corrupt records (round-2
        # lesson: BENCH_r02 carried a floor-clamped 1e-9)
        if 1e-4 < step < 10.0:
            return step, bench_file
    return None, None


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=os.path.join(REPO,
                                                      "SCALING.json"))
    parser.add_argument("--per-device-batch", type=int, default=128,
                        help="matches the bench.py single-chip batch "
                             "so t_step and t_comm describe one run")
    parser.add_argument("--size", type=int, default=227)
    parser.add_argument("--classes", type=int, default=1000)
    parser.add_argument("--counts", default="2,4,8,16,32,64")
    parser.add_argument("--ici-gbps", type=float, default=100.0,
                        help="usable all-reduce bandwidth GB/s per chip "
                             "(v5e 2D-torus derated)")
    parser.add_argument("--step-seconds", type=float, default=None,
                        help="single-chip step time from bench.py "
                             "(defaults to BENCH extras if present)")
    args = parser.parse_args()

    counts = [int(c) for c in args.counts.split(",")]
    measured, on_real_pod = measure(counts, args.per_device_batch,
                                    args.size, args.classes)

    grad_bytes = measured[-1]["allreduce_bytes"]
    analytic = measured[-1]["grad_bytes_analytic"]
    step_1 = args.step_seconds
    source = "flag"
    if step_1 is None:
        step_1, source = _bench_step_seconds()
    if step_1 is None:
        # only a TRUE single-chip row can seed the projection — an
        # n>=2 step time already contains all-reduce comm and would
        # double-count t_comm
        single = next((m for m in measured
                       if m["n"] == 1 and "step_seconds" in m), None)
        if on_real_pod and single:
            step_1 = single["step_seconds"]
            source = "measured on this pod (n=1)"
        else:
            sys.stderr.write(
                "ERROR: no trustworthy single-chip step time: no "
                "plausible BENCH_*.json record found and this host has "
                "no real TPU pod.  Pass --step-seconds from a real-chip "
                "bench run; refusing to project from oversubscribed-CPU "
                "times (they are not TPU-representative).\n")
            raise SystemExit(2)

    report = {
        "measured": measured,
        "measured_on": "real tpu pod" if on_real_pod
        else ("virtual cpu devices, compile-only "
              "(collective bytes; no step times — oversubscribed-CPU "
              "times are not TPU-representative)"),
        "model_config": {"size": args.size, "classes": args.classes,
                         "per_device_batch": args.per_device_batch},
        "allreduce_bytes_per_step": grad_bytes,
        "grad_pytree_bytes_analytic": analytic,
        "model": {
            "kind": "ring all-reduce, no overlap credited",
            "ici_usable_gbps": args.ici_gbps,
            "hop_latency_s": 1e-6,
            "single_chip_step_seconds": step_1,
            "step_seconds_source": source,
        },
        "projection": project(step_1, grad_bytes,
                              ici_gbps=args.ici_gbps),
        "sensitivity_at_64": {
            "bw_%.0fgbps_hop_%.0fus" % (gbps, hop * 1e6): project(
                step_1, grad_bytes, ici_gbps=gbps, hop_latency_s=hop,
                counts=(64,))["64"]["efficiency_pct"]
            for gbps in (args.ici_gbps / 2, args.ici_gbps,
                         args.ici_gbps * 2)
            for hop in (1e-6, 5e-6)
        },
        "target": {"efficiency_pct_8_to_64": 70.0,
                   "source": "BASELINE.md"},
    }
    # the 8->64 headline: efficiency(64) relative to efficiency(8)
    e8 = report["projection"]["8"]["efficiency_pct"]
    e64 = report["projection"]["64"]["efficiency_pct"]
    report["projected_8_to_64_relative_pct"] = round(100.0 * e64 / e8, 2)

    with open(args.out, "w") as fout:
        json.dump(report, fout, indent=1, sort_keys=True)
        fout.write("\n")
    print(json.dumps({"scaling_8_to_64_relative_pct":
                      report["projected_8_to_64_relative_pct"],
                      "absolute_efficiency_at_64_pct":
                      report["projection"]["64"]["efficiency_pct"],
                      "out": args.out}))


if __name__ == "__main__":
    main()
