"""QUANT.json — the int8 quantized-inference accuracy-parity receipt
(docs/serving.md "Quantized ladder").

Two zoo models (the mnist MLP and a conv stack) are trained to
decisiveness on seeded synthetic class data through the fused train
step, post-training-quantized (per-channel symmetric weights,
percentile activation calibration on a training-distribution stream),
and served through BOTH AOTEngine ladders in one process.  The
receipt records, per model:

- **top-1 accuracy** of the f32 and int8 engines on a held-out stream
  and their delta (the acceptance bound: <= 1 %), plus the raw
  prediction agreement and max softmax-probability divergence;
- the **bit-exactness** flag of the int8 Pallas matmul vs the jitted
  interpret-mode reference on the exact quantized operands the model
  serves (not a synthetic shape);
- **CPU latency rows** for both engines, honestly labeled: on CPU the
  int8 kernels execute through the Pallas INTERPRETER, so the int8
  leg's wall time measures the interpreter and carries no speedup
  claim — the TPU row (``bench.py quant_ab``, interleaved
  pass-filtered slopes against the int8 peak) is the real-hardware
  receipt the ROADMAP ledger tracks;
- warm-restart **compile receipts** for the quantized digests.

A compact ``quant_ab`` block is also folded into BENCH_serve.json so
the serving receipt carries the quantized ladder next to its
latency/throughput rows.

Run:  JAX_PLATFORMS=cpu python scripts/quant_receipt.py
"""

import json
import os
import sys
import time

import numpy

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _blob_data(rng, n, sample_shape, classes, sep=3.2, noise=1.0):
    """Seeded Gaussian class blobs with genuine overlap: the center
    spread scales as 1/sqrt(dim) so the pairwise separation along the
    discriminant is ~sep noise-sigmas REGARDLESS of dimensionality,
    landing the trained models in the ~90-98% top-1 band — the int8
    delta is then measured where decision boundaries actually live
    instead of on a saturated 100%-accuracy task where any delta
    would read as 0."""
    dim = int(numpy.prod(sample_shape))
    centers = rng.randn(classes, *sample_shape).astype(
        numpy.float32) * (sep / numpy.sqrt(dim))
    labels = rng.randint(0, classes, n).astype(numpy.int32)
    data = centers[labels] + rng.randn(
        n, *sample_shape).astype(numpy.float32) * noise
    return data, labels


def _train(plans, state, data, labels, batch=128, steps=80):
    """A short fused-step run — enough to make the heads decisive."""
    from veles_tpu.compiler import build_train_step

    step = build_train_step(plans, loss="softmax", donate=False)
    n = data.shape[0]
    for i in range(steps):
        lo = (i * batch) % (n - batch)
        state, metrics = step(state, data[lo:lo + batch],
                              labels[lo:lo + batch], float(batch))
    return state, {k: float(v) for k, v in metrics.items()}


def _latency_row(engine, x, reps=20):
    """Median whole-batch infer wall time (ms) — a CPU machinery
    number, labeled as such in the receipt."""
    engine.infer(x[:8])  # warm every rung the chunker will touch
    times = []
    for _ in range(reps):
        start = time.perf_counter()
        engine.infer(x)
        times.append(time.perf_counter() - start)
    return round(float(numpy.median(times)) * 1e3, 3)


def _receipt_for_model(name, specs, sample_shape, seed, train_n=4096,
                       eval_n=2048, steps=80, sep=3.2):
    import jax
    import jax.numpy as jnp

    from veles_tpu.backends import Device
    from veles_tpu.models.zoo import build_plans_and_state
    from veles_tpu.ops.matmul_int8 import (matmul_int8,
                                           matmul_int8_reference)
    from veles_tpu.quant import quantize_model_spec
    from veles_tpu.quant.forward import quantize_activation
    from veles_tpu.serve.engine import AOTEngine

    rng = numpy.random.RandomState(seed)
    classes = specs[-1]["output_sample_shape"]
    plans, state, _out_shape = build_plans_and_state(
        specs, sample_shape, seed=seed)
    data, labels = _blob_data(rng, train_n + eval_n, sample_shape,
                              classes, sep=sep)
    state, last_metrics = _train(plans, state, data[:train_n],
                                 labels[:train_n], steps=steps)
    params = [{"weights": None if s["weights"] is None
               else numpy.asarray(s["weights"]),
               "bias": None if s["bias"] is None
               else numpy.asarray(s["bias"])} for s in state]

    calib = data[:512]
    qparams, calibration = quantize_model_spec(plans, params, calib)

    device = Device(backend="cpu")
    ladder = (32, 128)
    engines = {}
    for leg, p in (("f32", params), ("int8", qparams)):
        engines[leg] = AOTEngine(plans, p, sample_shape, ladder=ladder,
                                 device=device)
        engines[leg].compile()

    x_eval = data[train_n:train_n + eval_n]
    y_eval = labels[train_n:train_n + eval_n]
    probs = {leg: engines[leg].infer(x_eval) for leg in engines}
    preds = {leg: probs[leg].argmax(1) for leg in engines}
    acc = {leg: float((preds[leg] == y_eval).mean()) for leg in preds}

    # kernel-vs-reference bit-exactness on the model's OWN quantized
    # weights: the contraction shape the served ladder runs (for a
    # conv entry, the im2col-flattened (taps*Cin, Cout) matrix), fed
    # grid-true int8 activations quantized on the entry's calibrated
    # scale
    q_entry = next(e for e in qparams if e.get("weights_scale")
                   is not None)
    w_q = jnp.asarray(q_entry["weights"].reshape(
        -1, q_entry["weights"].shape[-1]))
    act_scale = jnp.asarray(q_entry["act_scale"])
    a_q = quantize_activation(
        jnp.asarray(rng.rand(32, w_q.shape[0]).astype(numpy.float32)
                    * float(act_scale) * 127.0), act_scale)
    scale = jnp.asarray(q_entry["act_scale"]
                        * q_entry["weights_scale"])
    bias = jnp.asarray(q_entry["bias"])
    bitexact = bool(
        (numpy.asarray(matmul_int8(a_q, w_q, scale, bias)) ==
         numpy.asarray(jax.jit(matmul_int8_reference)(
             a_q, w_q, scale, bias))).all())

    return {
        "model": name,
        "sample_shape": list(sample_shape),
        "classes": int(classes),
        "train_steps": steps,
        "final_train_loss": round(last_metrics["loss"], 5),
        "eval_samples": eval_n,
        "top1_f32_pct": round(100 * acc["f32"], 3),
        "top1_int8_pct": round(100 * acc["int8"], 3),
        "top1_delta_pct": round(100 * abs(acc["f32"] - acc["int8"]),
                                3),
        "prediction_agreement_pct": round(
            100 * float((preds["f32"] == preds["int8"]).mean()), 3),
        "max_abs_dprob": float(numpy.abs(probs["f32"]
                                         - probs["int8"]).max()),
        "clip_fraction": round(calibration.clip_fraction, 6),
        "pallas_bitexact_vs_reference": bitexact,
        "digests": {leg: engines[leg].digest for leg in engines},
        "compile_receipts": {
            leg: {k: engines[leg].compile_receipt[k]
                  for k in ("backend_compiles", "cache_hits",
                            "new_compiles", "rungs", "quantized")}
            for leg in engines},
        "cpu_latency_ms_batch128": {
            leg: _latency_row(engines[leg], x_eval[:128])
            for leg in engines},
    }


def main():
    t0 = time.time()
    from veles_tpu.models.zoo import mnist_mlp_layers

    conv_specs = [
        {"type": "conv_str", "n_kernels": 8, "kx": 5, "ky": 5,
         "sliding": (1, 1), "padding": 2, "learning_rate": 0.02,
         "gradient_moment": 0.9},
        {"type": "max_pooling", "kx": 2, "ky": 2, "sliding": (2, 2)},
        {"type": "all2all_tanh", "output_sample_shape": 64,
         "learning_rate": 0.02, "gradient_moment": 0.9},
        {"type": "softmax", "output_sample_shape": 10,
         "learning_rate": 0.02, "gradient_moment": 0.9},
    ]
    models = [
        ("mnist_mlp_784_100_10",
         mnist_mlp_layers(lr=0.05), (784,), 13, 3.2),
        ("convnet_16x16_c8_p2_fc64_10", conv_specs, (16, 16, 1), 17,
         7.0),
    ]
    rows = [
        _receipt_for_model(name, specs, shape, seed, sep=sep)
        for name, specs, shape, seed, sep in models]

    import jax
    receipt = {
        "kind": "quantized-inference parity receipt "
                "(docs/serving.md 'Quantized ladder')",
        "schema": 1,
        "platform": jax.devices()[0].device_kind,
        "scheme": "w8a8 symmetric: per-channel weight scales, "
                  "per-tensor percentile-99.9 activation scales, "
                  "int32 accumulation, fused dequant epilogue "
                  "(ops/matmul_int8.py)",
        "acceptance": {
            "top1_delta_bound_pct": 1.0,
            "all_within_bound": all(
                r["top1_delta_pct"] <= 1.0 for r in rows),
            "all_bitexact": all(
                r["pallas_bitexact_vs_reference"] for r in rows),
        },
        "models": rows,
        "latency_note": (
            "cpu_latency_ms rows are CPU-interpreter machinery "
            "evidence only: the int8 Pallas kernels run through the "
            "Pallas interpreter on CPU, so the int8 leg measures the "
            "interpreter, not the MXU's 8-bit rate.  The TPU speedup "
            "row is bench.py quant_ab (interleaved pass-filtered "
            "slopes, int8-vs-bf16 peak context) — pending a "
            "real-TPU run (ROADMAP real-hardware receipts ledger)."),
        "wall_s": round(time.time() - t0, 1),
    }
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "QUANT.json")
    with open(out, "w") as fout:
        json.dump(receipt, fout, indent=1)
    print(json.dumps(receipt, indent=1))

    # fold the compact quantized block into BENCH_serve.json so the
    # serving receipt carries the quantized ladder beside its
    # latency/throughput rows
    bench_path = os.path.join(os.path.dirname(out), "BENCH_serve.json")
    try:
        with open(bench_path) as fin:
            bench = json.load(fin)
        bench["quant_ab"] = {
            "see": "QUANT.json",
            "platform": receipt["platform"],
            "models": {r["model"]: {
                "top1_delta_pct": r["top1_delta_pct"],
                "agreement_pct": r["prediction_agreement_pct"],
                "bitexact": r["pallas_bitexact_vs_reference"],
                "cpu_latency_ms_batch128":
                    r["cpu_latency_ms_batch128"],
            } for r in rows},
            "note": receipt["latency_note"],
        }
        with open(bench_path, "w") as fout:
            json.dump(bench, fout, indent=1)
        print("BENCH_serve.json: quant_ab block updated")
    except (OSError, ValueError) as exc:
        print("BENCH_serve.json not updated: %s" % exc)
    return 0


if __name__ == "__main__":
    sys.exit(main())
