"""Multi-host serve-tier chaos soak -> HEDGE.json receipt.

The acceptance proof of the fleet tier (docs/serving.md "Multi-host
tier", ISSUE 15): a front-tier :class:`FleetRouter` dispatching over
REAL serve-host subprocesses, with the two headline failure semantics
measured rather than assumed:

- **kill**: a seeded driver-side ``serve.host.preempt`` schedule
  SIGKILLs a serve host mid-stream while closed-loop clients hammer
  the fleet.  Every in-flight request on the dead link must be
  re-answered by survivors — **zero failed requests**, every answer
  bit-identical to the sequential single-engine reference — at
  bounded p99; the host then respawns against its digest-keyed
  persistent compile cache and rejoins with a **0-new-compiles**
  re-warm receipt before re-entering rotation (membership epochs
  bumped for the leave AND the rejoin).
- **hedge_ab**: EVERY host armed with seeded random stalls
  (``serve.host.stall`` on a fraction of each host's frames — the
  tail-at-scale shape: any request may straggle, so the
  throughput-EMA routing cannot simply learn to avoid one sick host;
  a PERSISTENT straggler is the routing weights' job, and the EMA
  penalty on cancelled hedge losers makes sure hedging never masks
  one).  Closed-loop p50/p95/p99 measured with hedging OFF then ON:
  hedging must measurably cut p99 — a stalled request is
  re-dispatched to a sibling past the throughput-corrected
  threshold, first result wins, losers rejected at the exactly-once
  fence.

Usage::

    python scripts/fleet_soak.py --out HEDGE.json          # full
    python scripts/fleet_soak.py --fast --out /tmp/H.json  # smoke
    python scripts/fleet_soak.py --tenants --out QOS.json  # QoS soak
    python scripts/fleet_soak.py --alerts --out ALERTS.json  # alerts

``--tenants`` reuses the same subprocess-host harness for the
multi-tenant QoS receipt (:func:`run_tenant_soak` -> QOS.json; see
scripts/qos_soak.py for the dedicated entry): a best-effort flood
plus seeded stalls against interactive SLO clients, then the fleet
canary promote/poison-rollback cycle.  The fast profile is the
slow-marked test in tests/test_serve_fleet.py (tests/test_qos.py for
``--tenants``); the full profile is the committed HEDGE.json /
QOS.json receipt.  (``--host`` is the internal serve-host subprocess
entry the driver spawns.)
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy  # noqa: E402

SAMPLE_SHAPE = (16,)
LADDER = (8, 32)  # starts at 8: rung-1 is the ~1-ulp odd one out


def _mlp_spec(seed):
    from veles_tpu.compiler import LayerPlan
    from veles_tpu.models.all2all import All2AllSoftmax, All2AllTanh
    rng = numpy.random.RandomState(seed)
    plans = [LayerPlan(All2AllTanh), LayerPlan(All2AllSoftmax)]
    params = [
        {"weights": rng.rand(16, 24).astype(numpy.float32),
         "bias": rng.rand(24).astype(numpy.float32)},
        {"weights": rng.rand(24, 4).astype(numpy.float32),
         "bias": rng.rand(4).astype(numpy.float32)},
    ]
    return plans, params


def _build_engine(seed, cache_root=None):
    from veles_tpu.backends import Device
    from veles_tpu.serve import AOTEngine
    plans, params = _mlp_spec(seed)
    engine = AOTEngine(plans, params, SAMPLE_SHAPE, ladder=LADDER,
                       device=Device(backend="cpu"),
                       cache_root=cache_root)
    return engine, engine.compile()


def host_main(args):
    """The serve-host subprocess: one engine + batcher behind the
    binary transport, identity + re-warm receipt on the READY line.
    VELES_CHAOS in the environment arms per-host faults (the
    straggler's ``serve.host.stall``); the driver's SIGKILL is the
    preemption."""
    from veles_tpu.serve import BinaryTransportServer, ContinuousBatcher
    engine, receipt = _build_engine(args.seed,
                                    cache_root=args.cache_root or None)
    batcher = ContinuousBatcher(engine, max_delay_s=0.001,
                                max_queue=4096).start()
    server = BinaryTransportServer(
        batcher, port=0, host_meta={"host_id": args.host_id})
    server.start_background()
    print("FLEET_HOST_READY port=%d host_id=%s new_compiles=%d"
          % (server.port, args.host_id, receipt["new_compiles"]),
          flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
        batcher.stop()
    return 0


class _HostProc(object):
    """Driver-side handle on one serve-host subprocess."""

    def __init__(self, host_id, seed, cache_root, chaos_spec=None):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("VELES_CHAOS", None)
        if chaos_spec:
            env["VELES_CHAOS"] = chaos_spec
        self.host_id = host_id
        self.proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--host",
             "--host-id", host_id, "--seed", str(seed),
             "--cache-root", cache_root],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True)
        deadline = time.monotonic() + 120.0
        self.port = None
        self.new_compiles = None
        while time.monotonic() < deadline:
            line = self.proc.stdout.readline()
            if not line:
                break
            if line.startswith("FLEET_HOST_READY"):
                fields = dict(kv.split("=") for kv in line.split()[1:])
                self.port = int(fields["port"])
                self.new_compiles = int(fields["new_compiles"])
                break
        if self.port is None:
            raise RuntimeError("host %s never came up" % host_id)

    def sigkill(self):
        os.kill(self.proc.pid, signal.SIGKILL)
        self.proc.wait(timeout=30)

    def stop(self):
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=10)


def _closed_loop(router, reference, clients, duration_s, on_ok=None):
    """Closed-loop client pool: every answer verified bit-identical to
    the sequential reference row.  Returns (latencies, failures,
    mismatches, ok_count)."""
    samples = reference["samples"]
    ref = reference["ref"]
    stop_at = time.perf_counter() + duration_s
    latencies, failures, mismatches = [], [], []
    lock = threading.Lock()

    def client(k):
        mine, bad, fail = [], 0, []
        n = 0
        while time.perf_counter() < stop_at:
            idx = (k * 131 + n) % len(samples)
            n += 1
            t0 = time.perf_counter()
            try:
                out = router.infer(samples[idx], timeout=30.0)
            except Exception as exc:  # EVERY failure is a drop
                fail.append("%s: %s" % (type(exc).__name__, exc))
                continue
            dt = time.perf_counter() - t0
            mine.append(dt)
            if not (out == ref[idx]).all():
                bad += 1
            if on_ok is not None:
                on_ok()
        with lock:
            latencies.extend(mine)
            failures.extend(fail)
            if bad:
                mismatches.append(bad)

    threads = [threading.Thread(target=client, args=(k,),
                                name="soak-client-%d" % k)
               for k in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return latencies, failures, mismatches


def _pcts(latencies):
    from veles_tpu.observe.metrics import percentiles
    return {p: round(v * 1e3, 3)
            for p, v in percentiles(latencies).items()}


def _counters(names):
    from veles_tpu.observe.metrics import registry
    return {name: registry.counter(name).value for name in names}


_COUNTERS = ("serve.fleet.requests", "serve.fleet.failed",
             "serve.fleet.requeues", "serve.fleet.cascades",
             "serve.hedge.fired", "serve.hedge.wins",
             "serve.hedge.duplicates_dropped")


def run_soak(seed=11, fast=False, out=None, p99_bound_s=2.0):
    from veles_tpu import chaos
    from veles_tpu.serve import FleetRouter

    workdir = tempfile.mkdtemp(prefix="fleet_soak_")
    engine, _ = _build_engine(seed)
    rng = numpy.random.RandomState(seed + 1)
    samples = rng.rand(64, *SAMPLE_SHAPE).astype(numpy.float32)
    reference = {"samples": samples,
                 "ref": engine.infer(samples)}

    # ---- phase A: SIGKILL a host mid-stream -----------------------------
    duration = 6.0 if fast else 20.0
    clients = 4 if fast else 6
    hosts = [_HostProc("h%d" % i, seed,
                       os.path.join(workdir, "cache_h%d" % i))
             for i in range(3)]
    router = FleetRouter(hedge_factor=2.0, hedge_floor_s=0.05,
                         hedge_tick_s=0.01).start()
    for h in hosts:
        router.add_host(address=("127.0.0.1", h.port),
                        host_id=h.host_id)
    before = _counters(_COUNTERS)
    epoch_before = router.fleet.membership_epoch

    # the kill/rejoin schedule is a SEEDED FaultPlan the driver fires
    # once per completed request — deterministic in request count, like
    # elastic_soak's driver-side slave.rejoin_after
    kill_after = 40 if fast else 150
    plan = (chaos.FaultPlan(seed=seed)
            .add("serve.host.preempt", "kill", nth=kill_after)
            .add("slave.rejoin_after", "", nth=1, param=1.0))
    kill_state = {"killed_at": None, "rejoined": None,
                  "rejoin_compiles": None, "thread": None}
    lock = threading.Lock()

    def on_ok():
        with lock:
            fault = plan.fire("serve.host.preempt")
        if fault is not None:
            # MID-STREAM means mid-stream: pull the trigger only once
            # the victim observably holds in-flight work (closed-loop
            # clients re-arm it within a millisecond), so the kill
            # provably orphans requests for the requeue path to save
            for _ in range(2000):
                if router.snapshot()["hosts"].get(
                        "h0", {}).get("inflight"):
                    break
                time.sleep(0.001)
            kill_state["killed_at"] = time.perf_counter()
            hosts[0].sigkill()

            def rejoin():
                delay = plan.fire("slave.rejoin_after")
                time.sleep(delay.param if delay is not None else 1.0)
                hosts[0] = respawned = _HostProc(
                    "h0", seed, os.path.join(workdir, "cache_h0"))
                router.add_host(address=("127.0.0.1", respawned.port),
                                host_id="h0-rejoin")
                kill_state["rejoined"] = time.perf_counter()
                kill_state["rejoin_compiles"] = respawned.new_compiles
            kill_state["thread"] = threading.Thread(target=rejoin,
                                                   name="rejoin")
            kill_state["thread"].start()

    latencies, failures, mismatches = _closed_loop(
        router, reference, clients, duration, on_ok=on_ok)
    if kill_state["thread"] is not None:
        # the respawn (subprocess + warm compile) may outlast a short
        # closed loop: the rejoin must land BEFORE the membership /
        # re-warm receipts are read (and before the router stops)
        kill_state["thread"].join(timeout=180)
    kill_counters = {
        name: value - before[name]
        for name, value in _counters(_COUNTERS).items()}
    kill_snap = router.snapshot()
    epochs_bumped = router.fleet.membership_epoch - epoch_before
    router.stop()
    for h in hosts:
        h.stop()
    p99_s = (sorted(latencies)[
        max(0, int(len(latencies) * 0.99) - 1)] if latencies else None)
    kill = {
        "clients": clients,
        "duration_s": duration,
        "requests_ok": len(latencies),
        "failed_requests": len(failures),
        "failed_detail": failures[:5],
        "bit_identical": not mismatches,
        "host_killed": kill_state["killed_at"] is not None,
        "rejoined": kill_state["rejoined"] is not None,
        "rejoin_new_compiles": kill_state["rejoin_compiles"],
        "membership_epochs_bumped": epochs_bumped,
        "latency_ms": _pcts(latencies),
        "p99_bound_s": p99_bound_s,
        "p99_within_bound": (p99_s is not None and
                             p99_s <= p99_bound_s),
        "counters": kill_counters,
        "fleet": kill_snap,
    }

    # ---- phase B: hedging A/B under induced stragglers ------------------
    # random stalls on EVERY host (independent seeded streams): the
    # tail-at-scale shape routing cannot dodge — hedging is the only
    # tail cure, which is exactly what the A/B must isolate
    leg_s = 4.0 if fast else 10.0
    # stall 5% of each host's frames 150 ms: single-stall probability
    # (~5%) dominates p99 in the OFF leg, while double-stall — the
    # case hedging cannot rescue, original AND hedge both stalled —
    # stays well under the 1% percentile boundary (~0.25%), so the ON
    # leg's p99 is the hedge path, not the stall
    stall = "seed=%d;serve.host.stall=stall:p0.05:0.15"
    legs = {}
    hedge_counts = {}
    for name, hedge_on in (("off", False), ("on", True)):
        # fresh hosts per leg: each chaos stream restarts at its seed,
        # so both legs face the same per-host stall patterns
        stallers = [
            _HostProc("s%d" % i, seed,
                      os.path.join(workdir, "cache_s%d" % i),
                      chaos_spec=stall % (seed + 100 * (i + 1)))
            for i in range(2)]
        router = FleetRouter(hedge=hedge_on, hedge_factor=2.0,
                             hedge_floor_s=0.03,
                             hedge_tick_s=0.005).start()
        for i, h in enumerate(stallers):
            router.add_host(address=("127.0.0.1", h.port),
                            host_id="s%d" % i)
        before = _counters(_COUNTERS)
        latencies, failures, mismatches = _closed_loop(
            router, reference, 4, leg_s)
        hedge_counts[name] = {
            k: v - before[k] for k, v in _counters(_COUNTERS).items()}
        router.stop()
        for h in stallers:
            h.stop()
        legs[name] = {
            "requests_ok": len(latencies),
            "failed_requests": len(failures),
            "bit_identical": not mismatches,
            "latency_ms": _pcts(latencies),
        }
    p99_off = legs["off"]["latency_ms"].get("p99")
    p99_on = legs["on"]["latency_ms"].get("p99")
    cut = (round(100.0 * (p99_off - p99_on) / p99_off, 2)
           if p99_off else None)
    hedge_ab = {
        "straggler_chaos": stall % seed +
            " (per host, independent seed offsets)",
        "off": legs["off"],
        "on": legs["on"],
        "hedges_fired": hedge_counts["on"]["serve.hedge.fired"],
        "hedge_wins": hedge_counts["on"]["serve.hedge.wins"],
        "duplicates_dropped":
            hedge_counts["on"]["serve.hedge.duplicates_dropped"],
        "p99_cut_pct": cut,
    }

    checks = {
        "zero_failed_requests": kill["failed_requests"] == 0 and
        legs["off"]["failed_requests"] == 0 and
        legs["on"]["failed_requests"] == 0,
        "bit_identical": kill["bit_identical"] and
        legs["off"]["bit_identical"] and legs["on"]["bit_identical"],
        "host_killed_mid_stream": kill["host_killed"],
        "requeued_in_flight": kill_counters["serve.fleet.requeues"] > 0,
        "membership_epochs_bumped": epochs_bumped >= 2,
        "rejoin_rewarm_zero_compiles":
            kill_state["rejoin_compiles"] == 0,
        "p99_within_bound": kill["p99_within_bound"],
        "hedging_cuts_p99": cut is not None and cut > 0,
    }
    receipt = {
        "schema": 1,
        "mode": "fast" if fast else "full",
        "seed": seed,
        "hosts": 3,
        "ladder": list(LADDER),
        "kill": kill,
        "hedge_ab": hedge_ab,
        "checks": checks,
        "passed": all(checks.values()),
    }
    if out:
        with open(out, "w") as fout:
            json.dump(receipt, fout, indent=1, sort_keys=True)
            fout.write("\n")
    print("fleet soak %s: %d ok / %d failed (kill phase, p99 %.1fms), "
          "requeues %d, rejoin compiles %s, hedge p99 cut %s%%"
          % ("PASSED" if receipt["passed"] else "FAILED",
             kill["requests_ok"], kill["failed_requests"],
             kill["latency_ms"].get("p99", float("nan")),
             kill_counters["serve.fleet.requeues"],
             kill_state["rejoin_compiles"], cut))
    return receipt


_QOS_COUNTERS = ("serve.fleet.shed",
                 "serve.tenant.interactive.shed",
                 "serve.tenant.batch.shed",
                 "serve.tenant.best_effort.shed",
                 "serve.hedge.fired",
                 "serve.hedge.budget_exhausted",
                 "serve.fleet.canary.mirrors",
                 "serve.fleet.canary.promotions",
                 "serve.fleet.canary.rollbacks")


def run_tenant_soak(seed=11, fast=False, out=None, slo_p99_s=2.0):
    """`--tenants` mode -> QOS.json (docs/serving.md "Multi-tenant
    QoS"): the same subprocess-host harness as the kill/hedge soak,
    pointed at the QoS contracts.

    - **flood**: a 3x best-effort tenant flood plus seeded per-host
      ``serve.host.stall`` stragglers against steady interactive
      clients through a ``--max-inflight``-bounded fleet front:
      interactive p99 must stay within the SLO budget, with **0
      interactive sheds** — every shed the flood causes attributed to
      best_effort/batch (the class-ordered eviction contract).
    - **canary**: :class:`FleetCanaryController` promotes a good
      snapshot host-by-host and auto-rolls back a class-permuted
      poison on real mirrored evidence — 0 failed interactive
      requests, 0 new compiles either way.  This phase runs the hosts
      in-process (socketpair adoption): ``LocalHostControl`` stages
      params straight into a host's engines, which is the driver-side
      stand-in for what a production host's freshness watcher does on
      its own machine.
    """
    from veles_tpu import chaos  # noqa: F401  (parity with run_soak)
    from veles_tpu.serve import FleetRouter, HedgeBudget, ServeOverload

    workdir = tempfile.mkdtemp(prefix="qos_soak_")
    engine, _ = _build_engine(seed)
    rng = numpy.random.RandomState(seed + 1)
    samples = rng.rand(64, *SAMPLE_SHAPE).astype(numpy.float32)
    reference = {"samples": samples, "ref": engine.infer(samples)}

    # ---- phase A: best-effort flood + stalls vs interactive SLO ---------
    duration = 6.0 if fast else 20.0
    clients = 3 if fast else 4
    flooders = 3  # the "3x" flood: 3 flooder threads per client pool
    stall = "seed=%d;serve.host.stall=stall:p0.05:0.15"
    hosts = [_HostProc("q%d" % i, seed,
                       os.path.join(workdir, "cache_q%d" % i),
                       chaos_spec=stall % (seed + 100 * (i + 1)))
             for i in range(2)]
    # the front bound is what the flood saturates: small enough that
    # eviction provably happens, large enough that the interactive
    # pool (clients << bound) never saturates it with its own class
    max_inflight = 32
    router = FleetRouter(hedge_factor=2.0, hedge_floor_s=0.03,
                         hedge_tick_s=0.01,
                         hedge_budget=HedgeBudget(),
                         max_inflight=max_inflight).start()
    for h in hosts:
        router.add_host(address=("127.0.0.1", h.port),
                        host_id=h.host_id)
    before = _counters(_QOS_COUNTERS)
    stop_at = time.perf_counter() + duration
    lock = threading.Lock()
    stats = {"latencies": [], "failures": [], "mismatches": 0,
             "interactive_sheds": 0, "flood_submitted": 0,
             "flood_shed": 0}

    def interactive_client(k):
        mine, fail, bad, sheds = [], [], 0, 0
        n = 0
        while time.perf_counter() < stop_at:
            idx = (k * 131 + n) % len(samples)
            n += 1
            t0 = time.perf_counter()
            try:
                out = router.infer(samples[idx], timeout=30.0,
                                   slo_class="interactive")
            except ServeOverload as exc:
                sheds += 1
                fail.append("ServeOverload: %s" % exc)
                continue
            except Exception as exc:
                fail.append("%s: %s" % (type(exc).__name__, exc))
                continue
            mine.append(time.perf_counter() - t0)
            if not (out == reference["ref"][idx]).all():
                bad += 1
        with lock:
            stats["latencies"].extend(mine)
            stats["failures"].extend(fail)
            stats["mismatches"] += bad
            stats["interactive_sheds"] += sheds

    def flooder(k):
        n, shed = 0, 0
        while time.perf_counter() < stop_at:
            try:
                # fire-and-forget: the storm wants the queue, not the
                # answers — exactly the noisy-neighbor shape
                router.submit(samples[(k * 17 + n) % 64],
                              slo_class="best_effort")
            except ServeOverload:
                shed += 1
            n += 1
            if n % 16 == 0:
                time.sleep(0.002)
        with lock:
            stats["flood_submitted"] += n
            stats["flood_shed"] += shed

    threads = [threading.Thread(target=interactive_client, args=(k,),
                                name="qos-int-%d" % k)
               for k in range(clients)]
    threads += [threading.Thread(target=flooder, args=(k,),
                                 name="qos-flood-%d" % k)
                for k in range(flooders)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # drain the storm's stragglers before reading counters/stopping
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline and \
            sum(router.snapshot()["unresolved"].values()):
        time.sleep(0.05)
    flood_counters = {name: value - before[name]
                      for name, value in _counters(_QOS_COUNTERS).items()}
    router.stop()
    for h in hosts:
        h.stop()
    flood = {
        "clients": clients,
        "flooders": flooders,
        "duration_s": duration,
        "max_inflight": max_inflight,
        "straggler_chaos": stall % seed +
            " (per host, independent seed offsets)",
        "interactive_ok": len(stats["latencies"]),
        "interactive_failed": len(stats["failures"]),
        "failed_detail": stats["failures"][:5],
        "interactive_sheds": stats["interactive_sheds"],
        "bit_identical": stats["mismatches"] == 0,
        "flood_submitted": stats["flood_submitted"],
        "flood_shed_client_side": stats["flood_shed"],
        "interactive_latency_ms": _pcts(stats["latencies"]),
        "slo_p99_bound_s": slo_p99_s,
        "counters": flood_counters,
    }
    p99 = (flood["interactive_latency_ms"] or {}).get("p99")

    # ---- phase B: fleet canary promote + poison rollback ----------------
    # in-process hosts: LocalHostControl needs engine access (see
    # docstring) — the router/mirror/judge path is the same code the
    # socketpair fleet tests and a remote fleet run
    import socket as _socket
    from veles_tpu.backends import Device
    from veles_tpu.serve import (
        AOTEngine, BinaryTransportServer, ContinuousBatcher)
    from veles_tpu.serve.freshness import (
        FleetCanaryController, LocalHostControl)

    plans, good = _mlp_spec(seed)
    poison = [dict(p) for p in good]
    poison[1] = dict(poison[1],
                     weights=numpy.ascontiguousarray(
                         good[1]["weights"][:, ::-1]),
                     bias=numpy.ascontiguousarray(good[1]["bias"][::-1]))
    entries = []
    for i in range(2):
        eng = AOTEngine(plans, good, SAMPLE_SHAPE, ladder=LADDER,
                        device=Device(backend="cpu"))
        eng.compile()
        batcher = ContinuousBatcher(eng, max_delay_s=0.002).start()
        server = BinaryTransportServer(
            batcher, port=None, host_meta={"host_id": "c%d" % i})
        server.start_background()
        entries.append((eng, batcher, server))
    router = FleetRouter(hedge=False).start()
    for _, _, server in entries:
        ours, theirs = _socket.socketpair()
        server.serve_socket(ours)
        router.add_host(sock=theirs)
    host_ids = sorted(router.snapshot()["hosts"])
    controls = {hid: LocalHostControl(entries[i][1])
                for i, hid in enumerate(host_ids)}
    controller = FleetCanaryController(
        router, controls, mirror_fraction=1.0, min_mirrors=8,
        divergence_limit=1e-4, breach_budget=2,
        verdict_timeout_s=60.0, seed=seed)
    canary_stats = {"failures": 0, "mismatches": 0, "served": 0}
    canary_stop = threading.Event()

    def canary_traffic():
        n = 0
        while not canary_stop.is_set():
            idx = n % len(samples)
            n += 1
            try:
                out = router.infer(samples[idx], timeout=30.0,
                                   slo_class="interactive")
            except Exception:
                canary_stats["failures"] += 1
                continue
            canary_stats["served"] += 1
            if not (out == reference["ref"][idx]).all():
                canary_stats["mismatches"] += 1

    traffic = threading.Thread(target=canary_traffic,
                               name="qos-canary-traffic")
    traffic.start()
    try:
        promote_receipt = controller.run(good, host_ids[0])
        rollback_receipt = controller.run(poison, host_ids[0])
    finally:
        canary_stop.set()
        traffic.join(timeout=30)
    # post-rollback: the fleet still answers with the good weights
    post_ok = all(
        (router.infer(samples[i], timeout=30.0)
         == reference["ref"][i]).all() for i in range(8))
    router.stop()
    for _, batcher, server in entries:
        server.stop()
        batcher.stop()
    canary = {
        "hosts": "2 in-process (socketpair adoption; see docstring)",
        "promote": promote_receipt,
        "rollback": rollback_receipt,
        "interactive_served": canary_stats["served"],
        "interactive_failed": canary_stats["failures"],
        "bit_identical": canary_stats["mismatches"] == 0,
        "post_rollback_bit_identical": post_ok,
    }

    checks = {
        "interactive_p99_within_slo": (p99 is not None and
                                       p99 / 1e3 <= slo_p99_s),
        "zero_interactive_sheds":
            stats["interactive_sheds"] == 0 and
            flood_counters["serve.tenant.interactive.shed"] == 0,
        "zero_interactive_failures": flood["interactive_failed"] == 0,
        "sheds_attributed_to_lower_classes":
            flood_counters["serve.tenant.best_effort.shed"] > 0,
        "flood_bit_identical": flood["bit_identical"],
        "canary_promoted":
            promote_receipt.get("verdict") == "promote",
        "canary_rolled_back":
            rollback_receipt.get("verdict") == "rolled_back",
        "canary_zero_new_compiles":
            promote_receipt.get("new_compiles") == 0 and
            rollback_receipt.get("new_compiles") == 0,
        "canary_zero_failed_interactive":
            canary["interactive_failed"] == 0,
        "canary_bit_identical": canary["bit_identical"] and
            canary["post_rollback_bit_identical"],
    }
    receipt = {
        "schema": 1,
        "mode": "fast" if fast else "full",
        "seed": seed,
        "ladder": list(LADDER),
        "flood": flood,
        "canary": canary,
        "checks": checks,
        "passed": all(checks.values()),
    }
    if out:
        with open(out, "w") as fout:
            json.dump(receipt, fout, indent=1, sort_keys=True)
            fout.write("\n")
    print("qos soak %s: interactive %d ok / %d failed / %d shed "
          "(p99 %.1fms), best_effort sheds %d, canary %s/%s "
          "(compiles %s/%s)"
          % ("PASSED" if receipt["passed"] else "FAILED",
             flood["interactive_ok"], flood["interactive_failed"],
             flood["interactive_sheds"],
             (p99 if p99 is not None else float("nan")),
             flood_counters["serve.tenant.best_effort.shed"],
             promote_receipt.get("verdict"),
             rollback_receipt.get("verdict"),
             promote_receipt.get("new_compiles"),
             rollback_receipt.get("new_compiles")))
    return receipt


def run_alert_soak(seed=11, fast=False, out=None):
    """``--alerts`` mode -> ALERTS.json (docs/observability.md "Fleet
    telemetry"): the burn-rate alerting plane proven on the same
    two-subprocess-host harness, positive AND negative:

    - **steady**: a quiet closed loop of interactive clients — the
      telemetry plane polls, rolls up, and sweeps the rules the whole
      time, and must fire ZERO alerts (a plane that pages on a
      healthy fleet is worse than no plane).
    - **stall**: the same loop with seeded ``serve.host.stall`` chaos
      parking 30% of frames 300 ms — far past the interactive budget,
      so the fleet-scope burn-rate pair (fast AND slow windows) must
      fire, and the firing must leave its evidence trail: a flight-
      recorder dump carrying the alert record and the tail-exemplar
      ring.
    - **rollup vs per-host evidence**: the merged latency digest's
      percentiles must be consistent with the per-host series the
      subprocesses actually shipped (count conservation; a mixture
      quantile lies within the component quantiles' envelope).
    - **perf gate**: the sentinel catches a planted regression in a
      bench record and passes the unmodified one.
    """
    from veles_tpu.observe import baseline as _baseline
    from veles_tpu.observe.flight import flight
    from veles_tpu.observe.timeseries import (
        FleetTelemetry, digest_percentiles, merge_digests, series)
    from veles_tpu.serve import FleetRouter

    workdir = tempfile.mkdtemp(prefix="alert_soak_")
    # soak-scale cadence: the subprocess hosts inherit the 0.25 s ring
    # interval through the environment; the front's already-built
    # global ring is retuned in place
    os.environ["VELES_SERIES_INTERVAL_S"] = "0.25"
    series.interval_s = 0.25
    # arm the flight recorder: a firing's dump IS part of the receipt
    flight.enabled = True
    flight.base_path = os.path.join(workdir, "flight")

    engine, _ = _build_engine(seed)
    rng = numpy.random.RandomState(seed + 1)
    samples = rng.rand(64, *SAMPLE_SHAPE).astype(numpy.float32)
    reference = {"samples": samples, "ref": engine.infer(samples)}

    duration = 8.0 if fast else 20.0
    clients = 3 if fast else 4
    # 30% of frames park 300 ms: the over-budget fraction (~0.3)
    # burns the 1% error budget ~30x in BOTH windows — far past the
    # 2x factor, while the steady leg's localhost-CPU tail sits well
    # under the 150 ms soak budget
    stall = "seed=%d;serve.host.stall=stall:p0.3:0.3"
    budgets = {"interactive": 0.15}
    legs = {}
    evidence = {}
    for leg_name, chaos_on in (("steady", False), ("stall", True)):
        hosts = [
            _HostProc("%s%d" % (leg_name, i), seed,
                      os.path.join(workdir,
                                   "cache_%s_%d" % (leg_name, i)),
                      chaos_spec=(stall % (seed + 100 * (i + 1))
                                  if chaos_on else None))
            for i in range(2)]
        # hedging OFF on purpose: the stall leg needs the straggler
        # tail to REACH the front-door latency digest — this soak
        # proves the pager, the hedge soak proves the cure
        from veles_tpu.serve import qos as _qos
        # alert_rules=[]: nothing may fire during warmup
        router = FleetRouter(hedge=False, telemetry_interval_s=0.25,
                             alert_rules=[]).start()
        for h in hosts:
            router.add_host(address=("127.0.0.1", h.port),
                            host_id=h.host_id)
        # warmup OUTSIDE the books: the fleet's first requests pay
        # connect + dispatch-path costs that would read as a real (but
        # uninteresting) budget breach in the steady leg
        _closed_loop_classed(router, reference, clients, 2.0,
                             slo_class="interactive")
        # then reset the plane (drop warmup buckets) and arm the
        # rules fresh: soak-scale budget, fleet scope — the
        # front-door digest is the one the stall reaches.  Wider-
        # than-default windows: soak cells are 0.25 s so the default
        # fast window (newest 3 cells) holds too few requests to
        # clear min_count and would abstain forever.
        router.telemetry = FleetTelemetry(interval_s=0.25)
        router.alerts.configure(
            _qos.burn_rule_specs(budgets=budgets, scope="fleet",
                                 fast_buckets=6, slow_buckets=24,
                                 min_count=10))
        fired_before = flight.dumps
        latencies, failures, mismatches = _closed_loop_classed(
            router, reference, clients, duration,
            slo_class="interactive")
        # one final poll round so buckets that closed at the tail of
        # the loop still ship and sweep before the books are read
        router._last_poll = 0.0
        router._poll_telemetry(time.perf_counter())
        time.sleep(1.0)
        alert_snap = router.alerts.snapshot()
        telemetry_snap = router.telemetry.snapshot()
        rollup = router.telemetry.rollup()
        per_host = {
            host: router.telemetry.host_buckets(host)
            for host in router.telemetry.hosts()}
        router.stop()
        for h in hosts:
            h.stop()
        legs[leg_name] = {
            "requests_ok": len(latencies),
            "failed_requests": len(failures),
            "bit_identical": not mismatches,
            "latency_ms": _pcts(latencies),
            "alerts_fired": alert_snap["fired_total"],
            "alerts": alert_snap,
            "flight_dumps_written": flight.dumps - fired_before,
            "offsets": {
                h: round(info.get("offset_s") or 0.0, 6)
                for h, info in
                (telemetry_snap.get("hosts") or {}).items()},
        }
        evidence[leg_name] = {"rollup": rollup, "per_host": per_host}

    # ---- rollup percentiles vs per-host evidence ------------------------
    # the host batcher's serve.latency_s digest ships from BOTH
    # subprocesses: merged count must equal the sum of per-host
    # counts, and the merged p50/p99 must lie within the per-host
    # envelope (a mixture quantile cannot leave it)
    hist_name = "serve.latency_s"
    host_digests = {}
    for host, buckets in evidence["stall"]["per_host"].items():
        if host == "front":
            continue  # the front has no batcher; host evidence only
        digests = [
            (b.get("hists") or {}).get(hist_name)
            for b in (buckets or ())]
        digests = [d for d in digests if d]
        if digests:
            host_digests[host] = merge_digests(digests)
    merged = merge_digests(host_digests.values())
    merged_pcts = digest_percentiles(merged)
    host_pcts = {host: digest_percentiles(d)
                 for host, d in host_digests.items()}
    count_ok = merged["count"] == sum(
        d["count"] for d in host_digests.values())
    envelope_ok = bool(host_pcts) and all(
        min(h[p] for h in host_pcts.values()) <= merged_pcts[p]
        <= max(h[p] for h in host_pcts.values())
        for p in ("p50", "p99") if merged_pcts.get(p) is not None)
    rollup_check = {
        "hist": hist_name,
        "hosts": sorted(host_digests),
        "merged_count": merged.get("count"),
        "per_host_counts": {h: d["count"]
                            for h, d in host_digests.items()},
        "count_conserved": count_ok,
        "merged_percentiles": merged_pcts,
        "per_host_percentiles": host_pcts,
        "within_host_envelope": envelope_ok,
    }

    # ---- perf-gate sentinel: planted regression must be caught ----------
    base = _baseline.load_baseline()
    gate_check = {"baseline": base.get("path") if base else None}
    if base and base.get("metrics"):
        clean = {name: row["value"]
                 for name, row in base["metrics"].items()}
        planted_metric = sorted(clean)[0]
        row = base["metrics"][planted_metric]
        tol = float(row.get("tolerance_pct", 10.0))
        sign = -1.0 if row.get("direction", "higher") == "higher" \
            else 1.0
        planted = dict(clean)
        planted[planted_metric] = row["value"] * (
            1.0 + sign * (2.0 * tol) / 100.0)
        clean_ok, _ = _baseline.gate(clean)
        planted_ok, planted_report = _baseline.gate(planted)
        gate_check.update({
            "clean_record_passes": clean_ok,
            "planted_metric": planted_metric,
            "planted_regression_caught": not planted_ok,
            "regressed": planted_report.get("regressed"),
        })

    stall_fired = [r["alert"] for r in
                   legs["stall"]["alerts"]["history"]
                   if r.get("state") == "firing"]
    firing = {r["alert"]: r for r in
              legs["stall"]["alerts"]["firing"]}
    burn_name = "slo_burn.fleet.interactive"
    burn_rec = firing.get(burn_name) or next(
        (r for r in legs["stall"]["alerts"]["history"]
         if r.get("alert") == burn_name and
         r.get("state") == "firing"), None)
    dump_path = (burn_rec or {}).get("flight_dump") or \
        flight.last_dump_path
    dump_has_exemplars = False
    if dump_path and os.path.exists(dump_path):
        try:
            with open(dump_path) as fh:
                doc = json.load(fh)
            # flight.dump merges ``extra`` keys at the document's top
            # level, next to the event ring
            dump_has_exemplars = bool(
                (doc.get("alert") or {}).get("alert") == burn_name
                and doc.get("exemplars"))
        except (OSError, ValueError):
            pass

    checks = {
        "steady_zero_alerts": legs["steady"]["alerts_fired"] == 0,
        "stall_burn_rate_fired": burn_name in stall_fired,
        "flight_dump_with_exemplars": dump_has_exemplars,
        "zero_failed_requests":
            legs["steady"]["failed_requests"] == 0 and
            legs["stall"]["failed_requests"] == 0,
        "bit_identical": legs["steady"]["bit_identical"] and
            legs["stall"]["bit_identical"],
        "rollup_count_conserved": rollup_check["count_conserved"],
        "rollup_within_host_envelope":
            rollup_check["within_host_envelope"],
        "gate_clean_passes": bool(gate_check.get(
            "clean_record_passes")),
        "gate_catches_planted_regression": bool(gate_check.get(
            "planted_regression_caught")),
    }
    receipt = {
        "schema": 1,
        "mode": "fast" if fast else "full",
        "seed": seed,
        "hosts": 2,
        "ladder": list(LADDER),
        "telemetry_interval_s": 0.25,
        "budgets_s": budgets,
        "straggler_chaos": stall % seed +
            " (stall leg only; per host, independent seed offsets)",
        "burn_rule": burn_name,
        "burn_firing": burn_rec,
        "flight_dump": dump_path,
        "steady": legs["steady"],
        "stall": legs["stall"],
        "rollup_check": rollup_check,
        "perf_gate": gate_check,
        "checks": checks,
        "passed": all(checks.values()),
    }
    if out:
        with open(out, "w") as fout:
            json.dump(receipt, fout, indent=1, sort_keys=True,
                      default=repr)
            fout.write("\n")
    print("alert soak %s: steady fired %d (want 0), stall fired %s, "
          "dump %s, rollup count %s envelope %s, gate planted=%s"
          % ("PASSED" if receipt["passed"] else "FAILED",
             legs["steady"]["alerts_fired"], stall_fired,
             "ok" if dump_has_exemplars else "MISSING",
             "ok" if count_ok else "BAD",
             "ok" if envelope_ok else "BAD",
             gate_check.get("planted_regression_caught")))
    return receipt


def _closed_loop_classed(router, reference, clients, duration_s,
                         slo_class=None):
    """_closed_loop with an SLO class on every request (the alert
    soak's interactive clients)."""
    samples = reference["samples"]
    ref = reference["ref"]
    stop_at = time.perf_counter() + duration_s
    latencies, failures, mismatches = [], [], []
    lock = threading.Lock()

    def client(k):
        mine, bad, fail = [], 0, []
        n = 0
        while time.perf_counter() < stop_at:
            idx = (k * 131 + n) % len(samples)
            n += 1
            t0 = time.perf_counter()
            try:
                out = router.infer(samples[idx], timeout=30.0,
                                   slo_class=slo_class)
            except Exception as exc:
                fail.append("%s: %s" % (type(exc).__name__, exc))
                continue
            mine.append(time.perf_counter() - t0)
            if not (out == ref[idx]).all():
                bad += 1
        with lock:
            latencies.extend(mine)
            failures.extend(fail)
            if bad:
                mismatches.append(bad)

    threads = [threading.Thread(target=client, args=(k,),
                                name="alert-client-%d" % k)
               for k in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return latencies, failures, mismatches


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--host", action="store_true",
                        help="internal: run as a serve-host subprocess")
    parser.add_argument("--host-id", default="host")
    parser.add_argument("--cache-root", default="")
    parser.add_argument("--seed", type=int, default=11)
    parser.add_argument("--fast", action="store_true",
                        help="smoke profile (the slow-marked test)")
    parser.add_argument("--tenants", action="store_true",
                        help="multi-tenant QoS soak -> QOS.json "
                        "(flood + fleet canary) instead of the "
                        "kill/hedge phases")
    parser.add_argument("--alerts", action="store_true",
                        help="telemetry/alerting soak -> ALERTS.json "
                        "(steady leg fires zero, stall leg fires the "
                        "burn-rate pair with its flight dump) instead "
                        "of the kill/hedge phases")
    parser.add_argument("--p99-bound-s", type=float, default=2.0,
                        help="absolute p99 bound for the kill phase "
                        "(CPU-scale; the bound is about NOT hanging, "
                        "the receipt records the measured value)")
    parser.add_argument("--slo-p99-s", type=float, default=2.0,
                        help="interactive p99 SLO budget for the "
                        "--tenants flood phase (CPU-scale)")
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)
    if args.host:
        return host_main(args)
    if args.alerts:
        receipt = run_alert_soak(seed=args.seed, fast=args.fast,
                                 out=args.out or "ALERTS.json")
        return 0 if receipt["passed"] else 1
    if args.tenants:
        receipt = run_tenant_soak(seed=args.seed, fast=args.fast,
                                  out=args.out or "QOS.json",
                                  slo_p99_s=args.slo_p99_s)
    else:
        receipt = run_soak(seed=args.seed, fast=args.fast,
                           out=args.out or "HEDGE.json",
                           p99_bound_s=args.p99_bound_s)
    return 0 if receipt["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
